"""AllReduceParameter — the distributed parameter-aggregation seam.

Reference parity: parameters/AllReduceParameter.scala:53-229, the
slice-owned parameter server over Spark's BlockManager:

  init           -> slice weights across N partitions          (:99-116)
  getWeights     -> all-gather FP16 weight slices              (:134-159)
  putGradients   -> send my gradient sliced to each owner      (:201-215)
  aggregate      -> owner sums its N incoming slices           (:161-199)
  sendWeight     -> republish my updated slice                 (:217-228)

TPU-native design: the five phases are THE two XLA collectives —
``reduce_scatter`` (putGradients+aggregate) and ``all_gather``
(sendWeight+getWeights) — over the mesh's data axis, or a single fused
``psum`` when slice ownership isn't wanted. This class keeps the
reference's slice bookkeeping (balanced ``task_size + (pid < extra)``
layout, :100-102) so optimizer state can be owned per-slice (ZeRO-1) and
checkpoints of sliced optimizer state stay layout-compatible.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.parallel.engine import get_mesh
from bigdl_tpu.parallel import collective as C
from bigdl_tpu.tensor import flatten_params

__all__ = ["AllReduceParameter", "slice_bounds", "GradientBuckets"]


def slice_bounds(size: int, partition_num: int, pid: int) -> tuple[int, int]:
    """Balanced slice layout (reference AllReduceParameter.scala:100-102:
    ``taskSize + (pid < extraSize ? 1 : 0)``). Returns (offset, length)."""
    task_size = size // partition_num
    extra = size % partition_num
    start = task_size * pid + min(pid, extra)
    length = task_size + (1 if pid < extra else 0)
    return start, length


class AllReduceParameter:
    """Collective-backed flat-parameter aggregation over the data axis."""

    def __init__(self, partition_num: int | None = None,
                 size: int | None = None,
                 *, axis: str = "data", mesh=None,
                 wire_dtype=jnp.bfloat16, wire_codec=None):
        self.mesh = mesh or get_mesh()
        self.axis = axis
        self.partition_num = partition_num or int(self.mesh.shape[axis])
        self.size = size
        self.wire_dtype = wire_dtype
        # a parameters.compression codec name ("bf16"/"int8") or WireCodec:
        # routes the gradient reduce-scatter through the wire-compressed
        # all_to_all construction and the weight all-gather through the
        # compressed payload path (the reference's FP16 wire, or int8).
        # None keeps the legacy wire_dtype cast semantics.
        from bigdl_tpu.parameters.compression import get_codec
        self.wire_codec = get_codec(wire_codec)
        self._unravel = None

    # -- canonical fused path (what DistriOptimizer compiles) --
    def all_reduce_gradients(self, per_shard_grads, *, mean: bool = True):
        """Reduce per-shard gradient pytrees into one global gradient.

        ``per_shard_grads``: a sequence of N gradient trees (one per mesh
        shard along ``axis``). Returns the mean (or sum) tree, replicated.
        A single tree is rejected — leaves whose leading dim happens to
        equal the mesh size would be silently mis-reduced. Note
        DistriOptimizer doesn't need this — its allreduce is induced by
        batch sharding inside the jitted step; this is the eager emulation
        of the reference's N-party protocol."""
        if not isinstance(per_shard_grads, (list, tuple)):
            raise ValueError(
                "all_reduce_gradients wants a sequence of N per-shard "
                "gradient trees (one per mesh shard), not a single tree")
        grads = jax.tree.map(lambda *ls: jnp.stack(ls), *per_shard_grads)
        return C.psum_tree(grads, self.axis, self.mesh, mean=mean,
                           wire_dtype=self.wire_dtype)

    # -- slice-owned path (reference's phase structure, ZeRO-style) --
    def init(self, parameter):
        """Record the flat layout (reference ``init`` slicing, :99-116)."""
        flat, unravel = flatten_params(parameter)
        self.size = int(flat.size)
        self._unravel = unravel
        return flat

    def put_gradients(self, per_shard_grads, *, mean: bool = False,
                      key=None):
        """reduce-scatter per-shard gradients: each mesh shard ends up
        owning the SUM (or mean) of its slice of the N distinct
        contributions (reference putGradients +
        aggregrateGradientPartition collapsed, :161-215).

        ``per_shard_grads``: a sequence of N gradient trees / flat vectors
        (one per shard), or a pre-stacked ``(N, S)`` array. Returns the
        sharded flat gradient of global shape ``(S,)``."""
        grads = per_shard_grads
        if isinstance(grads, (list, tuple)):
            flats = []
            for g in grads:
                if not (hasattr(g, "ndim") and g.ndim == 1):
                    g, _ = flatten_params(g)
                flats.append(g)
            stacked = jnp.stack(flats)
        else:
            if not hasattr(grads, "ndim") or grads.ndim != 2:
                raise ValueError(
                    "put_gradients wants N per-shard contributions (a "
                    "sequence of trees/vectors or an (N, S) stack); a "
                    "single replicated gradient/tree would be summed N "
                    "times")
            stacked = jnp.asarray(grads)
        pad = (-stacked.shape[1]) % self.partition_num
        if pad:
            stacked = jnp.concatenate(
                [stacked, jnp.zeros((stacked.shape[0], pad), stacked.dtype)],
                axis=1)
        if self.wire_codec is not None:
            return C.reduce_scatter(stacked, self.axis, self.mesh,
                                    mean=mean, codec=self.wire_codec,
                                    key=key)
        return C.reduce_scatter(stacked, self.axis, self.mesh, mean=mean,
                                wire_dtype=self.wire_dtype)

    def get_weights(self, sharded_flat):
        """all-gather the updated slices back into the full flat weight
        (reference sendWeightPartition + getWeights, :134-159,217-228).
        With a wire codec set the slices ride compressed, the
        reference's FP16 getWeights semantics."""
        full = C.all_gather(sharded_flat, self.axis, self.mesh,
                            codec=self.wire_codec)
        if self.size is not None:
            full = full[:self.size]
        return self._unravel(full) if self._unravel is not None else full

    def aggregate_gradient_partition(self, grads):
        """The reduce-scatter phase under its correctly spelled name
        (the reference method is misspelled
        ``aggregrateGradientPartition``, AllReduceParameter.scala:161)."""
        return self.put_gradients(grads)

    # reference-named alias (sic), kept for drop-in parity with scripts
    # written against the reference API
    aggregrate_gradient_partition = aggregate_gradient_partition


class GradientBuckets:
    """Size-targeted flat wire buckets over a params pytree.

    The bucketing layout behind the fully sharded weight update
    (optim/sharded_update.py): leaves are grouped — in REVERSE tree
    order, since backward produces the output-side layers' gradients
    first, so earlier buckets' collectives can overlap the rest of the
    backward — into dtype-homogeneous flat buckets of roughly
    ``bucket_bytes`` each, padded to a multiple of ``n_shards`` so every
    bucket splits into equal :func:`slice_bounds` slices (the
    AllReduceParameter layout, which keeps ZeRO-1 checkpoints
    compatible: state exported through :meth:`unflatten` is
    params-shaped regardless of bucket geometry)."""

    def __init__(self, tree, *, bucket_bytes: int = 4 << 20,
                 n_shards: int = 1):
        leaves, self._treedef = jax.tree.flatten(tree)
        if not leaves:
            raise ValueError("GradientBuckets needs a non-empty tree")
        self._shapes = [tuple(l.shape) for l in leaves]
        self._sizes = [int(np.prod(s)) if s else 1 for s in self._shapes]
        self._dtypes = [jnp.dtype(l.dtype) for l in leaves]
        order = list(range(len(leaves)))[::-1]
        self._buckets: list[dict] = []
        cur, cur_bytes, cur_dtype = [], 0, None
        for i in order:
            nbytes = self._sizes[i] * self._dtypes[i].itemsize
            if cur and (cur_dtype != self._dtypes[i]
                        or cur_bytes >= int(bucket_bytes)):
                self._close(cur, cur_dtype, n_shards)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nbytes
            cur_dtype = self._dtypes[i]
        if cur:
            self._close(cur, cur_dtype, n_shards)
        self.n_shards = int(n_shards)

    def _close(self, idxs, dtype, n_shards):
        size = sum(self._sizes[i] for i in idxs)
        self._buckets.append({
            "key": f"b{len(self._buckets):03d}",
            "idxs": list(idxs),
            "size": size,
            "padded": size + ((-size) % int(n_shards)),
            "dtype": dtype,
        })

    @property
    def keys(self) -> list[str]:
        return [b["key"] for b in self._buckets]

    @property
    def padded_sizes(self) -> dict:
        return {b["key"]: b["padded"] for b in self._buckets}

    def __len__(self) -> int:
        return len(self._buckets)

    def spec(self, leaf_spec) -> dict:
        """A {bucket key: leaf_spec} dict (shard_map spec helper)."""
        return {b["key"]: leaf_spec for b in self._buckets}

    def flatten(self, tree) -> dict:
        """Params-shaped tree -> {bucket key: padded flat vector}."""
        leaves = jax.tree.leaves(tree)
        if len(leaves) != len(self._sizes):
            raise ValueError(
                f"tree has {len(leaves)} leaves, bucket layout expects "
                f"{len(self._sizes)}")
        out = {}
        for b in self._buckets:
            parts = [jnp.ravel(leaves[i]) for i in b["idxs"]]
            pad = b["padded"] - b["size"]
            if pad:
                parts.append(jnp.zeros((pad,), b["dtype"]))
            out[b["key"]] = jnp.concatenate(parts) if len(parts) > 1 \
                else parts[0]
        return out

    def unflatten(self, bucket_dict) -> "object":
        """{bucket key: flat vector} -> params-shaped tree (padding
        dropped)."""
        leaves = [None] * len(self._sizes)
        for b in self._buckets:
            vec = bucket_dict[b["key"]]
            off = 0
            for i in b["idxs"]:
                n = self._sizes[i]
                leaves[i] = jnp.reshape(vec[off:off + n],
                                        self._shapes[i])
                off += n
        return jax.tree.unflatten(self._treedef, leaves)

"""Distributed parameter aggregation + wire codecs (reference
dl/.../bigdl/parameters/, SURVEY §2.6)."""

from bigdl_tpu.parameters.all_reduce import (AllReduceParameter,
                                             GradientBuckets, slice_bounds)
from bigdl_tpu.parameters.compression import (FP16CompressedTensor, compress,
                                              decompress, compressed_add,
                                              get_codec, KNOWN_CODECS)

"""Wire/storage compression codecs.

Reference parity: CompressedTensor/SerializerInstance (parameters/
Parameter.scala:25-69) and FP16CompressedTensor (FP16CompressedTensor.scala:
26-276): f32 -> "fp16" by keeping the TOP 16 bits of each IEEE float
(:267-275), with compressed-domain add for gradient aggregation.

That truncated format is bit-for-bit **bfloat16** — the reference was
shipping bf16 on the wire in 2016. On TPU this codec is therefore native:
``compress`` is a bf16 cast, compressed-domain ``add`` runs on the MXU/VPU.
Host-side (numpy) and device-side (jnp) variants are provided; the host path
is used for checkpoint shrinking and tests, the device path rides inside
jitted steps as ``wire_dtype=jnp.bfloat16``.

Device-side wire codecs (ISSUE 7): jit-composable row-wise codecs used by
the sharded-update collectives (optim/sharded_update.py,
parallel/collective.py). ``bf16`` ships the reference's exact uint16
high-bits wire format (bitcast, so no backend can silently promote the
payload back to f32); ``int8`` adds symmetric per-row quantization with
optional stochastic rounding — the unbiased form the error-feedback
gradient path uses (docs/PERFORMANCE.md).
"""
from __future__ import annotations

import numpy as np

__all__ = ["FP16CompressedTensor", "compress", "decompress",
           "compressed_add",
           "bf16_compress_device", "bf16_decompress_device",
           "int8_quantize", "int8_dequantize",
           "WireCodec", "FP32Codec", "BF16Codec", "Int8Codec",
           "get_codec", "KNOWN_CODECS"]


def compress(arr: np.ndarray) -> np.ndarray:
    """f32 -> uint16 of the high bits (== bfloat16 bit pattern), reference
    FP16CompressedTensor.toFP16 (:267-275)."""
    a = np.ascontiguousarray(arr, np.float32)
    return (a.view(np.uint32) >> 16).astype(np.uint16)


def decompress(comp: np.ndarray) -> np.ndarray:
    """uint16 high bits -> f32 with zeroed mantissa tail."""
    return (comp.astype(np.uint32) << 16).view(np.float32)


def compressed_add(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Add in the compressed domain (reference ``parAdd``/``add``,
    FP16CompressedTensor.scala:118-265): decompress, add, re-truncate."""
    return compress(decompress(a) + decompress(b))


class FP16CompressedTensor:
    """Object form mirroring the reference class."""

    def __init__(self, tensor_or_bytes):
        if isinstance(tensor_or_bytes, np.ndarray) and \
                tensor_or_bytes.dtype == np.uint16:
            self._comp = tensor_or_bytes.copy()
        else:
            self._comp = compress(np.asarray(tensor_or_bytes))

    def bytes(self, offset: int = 0, length: int | None = None) -> bytes:
        """(reference CompressedTensor.bytes)"""
        view = self._comp[offset:None if length is None else offset + length]
        return view.tobytes()

    @property
    def size(self) -> int:
        return self._comp.size

    def compress(self, tensor: np.ndarray, offset: int = 0) -> None:
        c = compress(np.asarray(tensor))
        self._comp[offset:offset + c.size] = c

    def decompress(self, tensor: np.ndarray | None = None,
                   offset: int = 0, length: int | None = None):
        """Write back into ``tensor`` (reference deCompress) or return."""
        out = decompress(self._comp[offset:None if length is None
                                    else offset + length])
        if tensor is not None:
            tensor.reshape(-1)[:out.size] = out
            return tensor
        return out

    def add(self, other: "FP16CompressedTensor | np.ndarray",
            offset: int = 0) -> "FP16CompressedTensor":
        o = other._comp if isinstance(other, FP16CompressedTensor) \
            else compress(np.asarray(other))
        self._comp[offset:offset + o.size] = compressed_add(
            self._comp[offset:offset + o.size], o)
        return self

    par_add = add  # the reference's multi-threaded variant — XLA/NumPy
    # vectorize it; kept as an alias for API parity


# ---------------------------------------------------------------------------
# Device-side codecs (jit-composable). jnp imports stay inside the
# functions so the host-side checkpoint/test path above never touches a
# backend.
# ---------------------------------------------------------------------------

# keeps an all-zero row's scale finite: q = 0, dequant = 0, exact
_SCALE_FLOOR = 1e-30


def bf16_compress_device(x):
    """f32 -> uint16 high bits on DEVICE — BIT-EXACT host ``compress``
    parity: the reference truncates (keeps the high 16 bits,
    FP16CompressedTensor.scala:267-275), so this shifts bits rather than
    casting to bf16, which would round to nearest. Shipping the uint16
    bit pattern also pins the wire width: backends that promote bf16
    compute to f32 (XLA:CPU) cannot widen an integer payload."""
    import jax
    import jax.numpy as jnp
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    return (bits >> 16).astype(jnp.uint16)


def bf16_decompress_device(comp):
    """uint16 high bits -> f32 on device (host ``decompress`` parity)."""
    import jax
    import jax.numpy as jnp
    return jax.lax.bitcast_convert_type(
        comp.astype(jnp.uint32) << 16, jnp.float32)


def int8_quantize(x, key=None):
    """Symmetric int8 quantization over the LAST axis: ``x`` ``(..., k)``
    -> ``(q int8 (..., k), scale (...,))`` with ``scale = amax/127``.

    ``key`` enables stochastic rounding — ``floor(y + u)``, ``u ~ U[0,1)``
    — which is unbiased (``E[q] = y``); the property the error-feedback
    gradient path relies on. ``key=None`` rounds to nearest
    (deterministic; used for the weight all-gather wire)."""
    import jax
    import jax.numpy as jnp
    scale = jnp.max(jnp.abs(x), axis=-1) / 127.0 + _SCALE_FLOOR
    y = x / scale[..., None]
    if key is not None:
        q = jnp.floor(y + jax.random.uniform(key, x.shape))
    else:
        q = jnp.round(y)
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale.astype(
        jnp.float32)


def int8_dequantize(q, scale):
    import jax.numpy as jnp
    return q.astype(jnp.float32) * scale[..., None]


class WireCodec:
    """Row-wise wire codec protocol for the sharded-update collectives.

    ``encode(x, key=None)`` maps a float32 ``(..., k)`` array to a dict of
    wire arrays (what actually rides the collective); ``decode(enc)``
    inverts it to f32. ``error_feedback`` marks lossy codecs whose
    gradient path carries a residual (optim/sharded_update.py);
    ``wire_bytes_per_element`` is the payload width the bench accounting
    expects on the wire."""

    name = "fp32"
    error_feedback = False
    stochastic = False
    wire_bytes_per_element = 4.0

    def encode(self, x, key=None):
        return {"q": x}

    def decode(self, enc):
        return enc["q"]


class FP32Codec(WireCodec):
    """Identity codec — explicit collectives at full width."""


class BF16Codec(WireCodec):
    """The reference's FP16CompressedTensor wire (uint16 high bits)."""

    name = "bf16"
    wire_bytes_per_element = 2.0

    def encode(self, x, key=None):
        return {"q": bf16_compress_device(x)}

    def decode(self, enc):
        return bf16_decompress_device(enc["q"])


class Int8Codec(WireCodec):
    """Symmetric per-row int8 + f32 scale; stochastic rounding when a
    key is supplied, error-feedback residual on the gradient path."""

    name = "int8"
    error_feedback = True
    stochastic = True
    wire_bytes_per_element = 1.0

    def encode(self, x, key=None):
        q, scale = int8_quantize(x, key)
        return {"q": q, "scale": scale}

    def decode(self, enc):
        return int8_dequantize(enc["q"], enc["scale"])


KNOWN_CODECS = ("fp32", "bf16", "int8")
_CODECS = {"fp32": FP32Codec, "bf16": BF16Codec, "int8": Int8Codec}


def get_codec(name) -> "WireCodec | None":
    """Resolve a wire-codec name (or pass through a WireCodec / None).

    ``None`` means "no explicit codec": callers treat it as
    uncompressed implicit collectives (the bit-identical sharded-update
    path), distinct from ``"fp32"`` which forces the explicit
    full-width wire."""
    if name is None or isinstance(name, WireCodec):
        return name
    try:
        return _CODECS[str(name)]()
    except KeyError:
        raise ValueError(
            f"unknown wire codec {name!r} (known: {KNOWN_CODECS})") from None

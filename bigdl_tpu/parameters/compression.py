"""Wire/storage compression codecs.

Reference parity: CompressedTensor/SerializerInstance (parameters/
Parameter.scala:25-69) and FP16CompressedTensor (FP16CompressedTensor.scala:
26-276): f32 -> "fp16" by keeping the TOP 16 bits of each IEEE float
(:267-275), with compressed-domain add for gradient aggregation.

That truncated format is bit-for-bit **bfloat16** — the reference was
shipping bf16 on the wire in 2016. On TPU this codec is therefore native:
``compress`` is a bf16 cast, compressed-domain ``add`` runs on the MXU/VPU.
Host-side (numpy) and device-side (jnp) variants are provided; the host path
is used for checkpoint shrinking and tests, the device path rides inside
jitted steps as ``wire_dtype=jnp.bfloat16``.
"""
from __future__ import annotations

import numpy as np

__all__ = ["FP16CompressedTensor", "compress", "decompress",
           "compressed_add"]


def compress(arr: np.ndarray) -> np.ndarray:
    """f32 -> uint16 of the high bits (== bfloat16 bit pattern), reference
    FP16CompressedTensor.toFP16 (:267-275)."""
    a = np.ascontiguousarray(arr, np.float32)
    return (a.view(np.uint32) >> 16).astype(np.uint16)


def decompress(comp: np.ndarray) -> np.ndarray:
    """uint16 high bits -> f32 with zeroed mantissa tail."""
    return (comp.astype(np.uint32) << 16).view(np.float32)


def compressed_add(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Add in the compressed domain (reference ``parAdd``/``add``,
    FP16CompressedTensor.scala:118-265): decompress, add, re-truncate."""
    return compress(decompress(a) + decompress(b))


class FP16CompressedTensor:
    """Object form mirroring the reference class."""

    def __init__(self, tensor_or_bytes):
        if isinstance(tensor_or_bytes, np.ndarray) and \
                tensor_or_bytes.dtype == np.uint16:
            self._comp = tensor_or_bytes.copy()
        else:
            self._comp = compress(np.asarray(tensor_or_bytes))

    def bytes(self, offset: int = 0, length: int | None = None) -> bytes:
        """(reference CompressedTensor.bytes)"""
        view = self._comp[offset:None if length is None else offset + length]
        return view.tobytes()

    @property
    def size(self) -> int:
        return self._comp.size

    def compress(self, tensor: np.ndarray, offset: int = 0) -> None:
        c = compress(np.asarray(tensor))
        self._comp[offset:offset + c.size] = c

    def decompress(self, tensor: np.ndarray | None = None,
                   offset: int = 0, length: int | None = None):
        """Write back into ``tensor`` (reference deCompress) or return."""
        out = decompress(self._comp[offset:None if length is None
                                    else offset + length])
        if tensor is not None:
            tensor.reshape(-1)[:out.size] = out
            return tensor
        return out

    def add(self, other: "FP16CompressedTensor | np.ndarray",
            offset: int = 0) -> "FP16CompressedTensor":
        o = other._comp if isinstance(other, FP16CompressedTensor) \
            else compress(np.asarray(other))
        self._comp[offset:offset + o.size] = compressed_add(
            self._comp[offset:offset + o.size], o)
        return self

    par_add = add  # the reference's multi-threaded variant — XLA/NumPy
    # vectorize it; kept as an alias for API parity

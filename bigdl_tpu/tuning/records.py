"""Persistent tuning records: measured kernel/knob winners, keyed by
(kernel, abstract-shape signature, device kind).

The autotuner (``tuning/autotuner.py``) writes one record per winning
configuration; consumers — the Pallas kernels' block pickers, the
sharded-update bucket sizing — look their key up at trace time and fall
back to their static menus on a miss, so a record file is always an
optimization and never a correctness dependency.

File format (JSON, one file for the whole fleet to share):

.. code-block:: json

    {"version": 1,
     "records": {
       "flash_attention|TPU v5e|skv=4096,sq=4096": {
         "config": {"bq": 512, "bk": 1024},
         "score": 0.00132, "meta": {"iters": 5}}}}

The key is ``kernel|device_kind|signature`` — a restarting worker on the
same chip generation adopts the fleet's tuned tiles; a different device
kind misses and re-tunes rather than importing another chip's winners.

Lookup cost matters: the kernel pickers consult records on EVERY trace,
so ``lookup`` is one dict probe on an in-memory index; the file is read
once (lazily) and written atomically on ``record``.

HOST-ONLY CONTRACT (jaxlint JX5): no module-level jax import — jax is
touched only inside :func:`device_kind`, lazily, to read the accelerator
name.
"""
from __future__ import annotations

import json
import logging
import os
import threading

__all__ = ["TuningRecords", "default_records", "set_default_records",
           "device_kind", "signature_str", "PATH_ENV"]

logger = logging.getLogger("bigdl_tpu.tuning")

#: environment variable naming the shared record file; when unset the
#: default store is in-memory only (still consultable/settable in-process)
PATH_ENV = "BIGDL_TPU_TUNING_FILE"

_VERSION = 1


def device_kind() -> str:
    """Accelerator name the records are keyed by (e.g. ``TPU v5e``).
    Best-effort: an uninitializable backend reports ``unknown`` rather
    than failing the lookup path."""
    try:
        import jax
        d = jax.devices()[0]
        return str(getattr(d, "device_kind", None) or d.platform)
    except Exception:
        return "unknown"


def signature_str(sig) -> str:
    """Canonical, order-independent string form of a signature: dicts
    and (name, value) pair tuples become sorted ``k=v`` lists; anything
    else falls back to ``repr``. The same logical signature must always
    produce the same key across processes."""
    if isinstance(sig, dict):
        items = sig.items()
    elif (isinstance(sig, (list, tuple))
          and all(isinstance(p, (list, tuple)) and len(p) == 2
                  for p in sig)):
        items = sig
    else:
        return repr(sig)
    return ",".join(f"{k}={v}" for k, v in sorted(
        ((str(k), v) for k, v in items)))


class TuningRecords:
    """One JSON-backed record store. ``path=None`` keeps the store
    in-memory (tests, or tuning without persistence)."""

    def __init__(self, path: str | None = None):
        self.path = path
        self._lock = threading.Lock()
        self._entries: dict[str, dict] = {}
        self._loaded = path is None

    # -- persistence ---------------------------------------------------
    def _ensure_loaded(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            entries = doc.get("records", {})
            if not isinstance(entries, dict):
                raise ValueError("records is not an object")
            self._entries = entries
        except FileNotFoundError:
            pass
        except Exception as e:
            # a corrupt record file must never take training down —
            # start empty and let re-tuning rebuild it
            logger.warning("tuning records %s unreadable (%s) — "
                           "starting empty", self.path, e)
            self._entries = {}

    def _save_locked(self) -> None:
        if self.path is None:
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": _VERSION, "records": self._entries},
                      f, indent=1, sort_keys=True)
        os.replace(tmp, self.path)   # atomic: readers see old or new

    # -- the API -------------------------------------------------------
    @staticmethod
    def key(kernel: str, sig, device: str | None = None) -> str:
        return f"{kernel}|{device or device_kind()}|{signature_str(sig)}"

    def lookup(self, kernel: str, sig, device: str | None = None
               ) -> dict | None:
        """The winning config dict for (kernel, signature) on this
        device kind, or None. One dict probe after the lazy file read."""
        with self._lock:
            self._ensure_loaded()
            e = self._entries.get(self.key(kernel, sig, device))
        return dict(e["config"]) if e and "config" in e else None

    def record(self, kernel: str, sig, config: dict, *,
               score: float | None = None, device: str | None = None,
               meta: dict | None = None) -> str:
        """Persist one winner; returns the record key."""
        k = self.key(kernel, sig, device)
        entry: dict = {"config": dict(config)}
        if score is not None:
            entry["score"] = float(score)
        if meta:
            entry["meta"] = dict(meta)
        with self._lock:
            self._ensure_loaded()
            self._entries[k] = entry
            self._save_locked()
        logger.info("tuning record %s -> %s (score %s)", k, config, score)
        return k

    def entries(self) -> dict:
        with self._lock:
            self._ensure_loaded()
            return {k: dict(v) for k, v in self._entries.items()}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._loaded = self.path is None
            if self.path is not None:
                try:
                    os.unlink(self.path)
                except FileNotFoundError:
                    pass
                self._loaded = True


_default: TuningRecords | None = None
_default_explicit = False
_default_lock = threading.Lock()


def default_records() -> TuningRecords:
    """The process-wide store: an explicitly-set one wins; otherwise
    backed by ``$BIGDL_TPU_TUNING_FILE`` when set, in-memory
    otherwise."""
    global _default
    with _default_lock:
        if _default_explicit and _default is not None:
            return _default
        path = os.environ.get(PATH_ENV) or None
        if _default is None or _default.path != path:
            _default = TuningRecords(path)
        return _default


def set_default_records(records: TuningRecords | None) -> None:
    """Swap the process-wide store (tests isolate with this). ``None``
    re-derives from the environment on next use."""
    global _default, _default_explicit
    with _default_lock:
        _default = records
        _default_explicit = records is not None

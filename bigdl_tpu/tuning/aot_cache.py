"""Persistent AOT executable cache: load compiled steps instead of
recompiling them (ROADMAP 4 — fleet cold-start).

Every worker restart today pays the full XLA compile for every step
signature it meets. This module makes step construction an explicit

    lower -> compile -> cache

pipeline: the compiled executable is serialized
(``jax.experimental.serialize_executable`` — the backend's own
executable serialization, NOT a re-traceable StableHLO export) under a
key derived from

- the step's **abstract shape signature** (what jax retraces on),
- the **mesh** (axis names, shape, device kinds, process count),
- the **donation mask** (a donated-argument executable is not
  interchangeable with an undonated one),
- a **library + device fingerprint** (jax/jaxlib versions, backend,
  device kind — a jaxlib upgrade or a different chip generation must
  miss, never reuse a stale binary),
- caller-supplied **extra** key material (the optimizer fingerprints its
  model/criterion/optim-method configuration here, since hyperparameters
  like the learning rate are compiled into the executable as constants).

A restarting or newly-elastic worker with a warm cache directory reaches
its first step in deserialize time (~10 ms) instead of compile time
(seconds to minutes) — measured by the ``compile_cold_start`` bench row.

Correctness backstop: ANY failure on the load path — unreadable blob,
deserialization error, backend rejection — logs a structured
``tuning_cache_miss`` with the reason, counts it in the registry
(``tuning_cache_misses_total``), and falls back to a fresh
lower/compile whose result re-populates the cache. A cache directory
can be deleted at any time; it is never a correctness dependency.
Executions from cache are BIT-IDENTICAL to fresh compiles (same
backend binary — pinned in tests/test_tuning.py).

HOST-ONLY CONTRACT (jaxlint JX5): jax imports live inside functions.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import re
import threading

__all__ = ["AOTCache", "StepCompiler", "cache_key", "env_cache",
           "fingerprint", "mesh_descriptor", "stable_repr", "PATH_ENV"]

logger = logging.getLogger("bigdl_tpu.tuning")

#: environment variable naming the cache directory; optimizers with no
#: explicit ``set_aot_cache`` pick it up so a fleet can be warmed by env
PATH_ENV = "BIGDL_TPU_AOT_CACHE_DIR"

_ADDR = re.compile(r" at 0x[0-9a-fA-F]+")


def stable_repr(obj) -> str:
    """``repr`` with memory addresses stripped — key material must be
    identical across processes or the fleet never shares a cache."""
    return _ADDR.sub("", repr(obj))


def fingerprint() -> dict:
    """Library + device identity baked into every key. Any field
    changing ⇒ a miss (no stale-executable reuse across jaxlib
    upgrades, backends, or chip generations)."""
    import jax
    import jaxlib
    try:
        d = jax.devices()[0]
        backend = d.platform
        kind = str(getattr(d, "device_kind", "") or backend)
    except Exception:
        backend, kind = "uninitialized", "unknown"
    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__,
            "backend": backend, "device_kind": kind,
            "processes": _process_count()}


def _process_count() -> int:
    try:
        import jax
        return int(jax.process_count())
    except Exception:
        return 1


def mesh_descriptor(mesh) -> tuple | None:
    """The key's mesh component: axis names + sizes and the device-kind
    set. Device IDs are deliberately EXCLUDED — the same program on the
    same mesh shape must hit regardless of which physical hosts joined
    the slice (that is the elastic-restart case)."""
    if mesh is None:
        return None
    kinds = sorted({str(getattr(d, "device_kind", d.platform))
                    for d in mesh.devices.flat})
    return (tuple(mesh.axis_names),
            tuple(int(mesh.shape[a]) for a in mesh.axis_names),
            tuple(kinds))


def cache_key(name: str, signature, *, mesh=None, donate_argnums=(),
              extra=None, fp: dict | None = None) -> str:
    """sha256 hex over the canonical JSON of all key components."""
    doc = {
        "name": name,
        "signature": stable_repr(signature),
        "mesh": mesh_descriptor(mesh),
        "donate": sorted(int(i) for i in donate_argnums),
        "fingerprint": fp if fp is not None else fingerprint(),
        "extra": stable_repr(extra) if extra is not None else None,
    }
    blob = json.dumps(doc, sort_keys=True, default=stable_repr)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


class AOTCache:
    """One cache directory of serialized executables (``<key>.exe``).

    Writes are atomic (temp file + rename), so concurrent workers
    warming the same shared directory race benignly — last writer wins
    with an identical payload. ``hits``/``misses`` count this
    instance's traffic; the process-wide registry carries
    ``tuning_cache_{hits,misses}_total`` per step name.
    """

    def __init__(self, path: str, *, watch=None):
        self.path = str(path)
        self._watch = watch
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        os.makedirs(self.path, exist_ok=True)

    def _file(self, key: str) -> str:
        return os.path.join(self.path, f"{key}.exe")

    def _count(self, name: str, hit: bool, reason: str | None = None):
        with self._lock:
            if hit:
                self.hits += 1
            else:
                self.misses += 1
        watch = self._watch
        if watch is None:
            from bigdl_tpu.observability.compile_watch import default_watch
            watch = default_watch()
        try:
            if hit:
                watch.note_cache_hit(name)
            else:
                watch.note_cache_miss(name, reason or "unknown")
        except Exception:       # telemetry must never break the pipeline
            pass

    def load(self, key: str, *, name: str = "step"):
        """The compiled executable for ``key``, or None (counted +
        reason-logged) when absent or unloadable. Never raises."""
        path = self._file(key)
        if not os.path.exists(path):
            self._count(name, False, "absent")
            return None
        try:
            with open(path, "rb") as f:
                blob = pickle.load(f)
            payload, in_tree, out_tree = (blob["payload"],
                                          blob["in_tree"],
                                          blob["out_tree"])
            from jax.experimental import serialize_executable as se
            compiled = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:
            # the backstop: a bad blob is a miss, not a crash — fresh
            # compilation follows and overwrites it
            logger.warning("tuning_cache_miss name=%s key=%s "
                           "reason=deserialize_failed error=%r — "
                           "falling back to fresh compile", name,
                           key[:12], e)
            self._count(name, False, f"deserialize_failed: {e}")
            return None
        self._count(name, True)
        logger.info("tuning_cache_hit name=%s key=%s (%d bytes)", name,
                    key[:12], len(payload))
        return compiled

    def store(self, key: str, compiled, *, name: str = "step",
              meta: dict | None = None) -> bool:
        """Serialize ``compiled`` under ``key``; best-effort (an
        unserializable executable — some backends — just leaves the
        cache cold). Returns True on a successful write."""
        try:
            from jax.experimental import serialize_executable as se
            payload, in_tree, out_tree = se.serialize(compiled)
            blob = {"payload": payload, "in_tree": in_tree,
                    "out_tree": out_tree, "meta": dict(meta or {},
                                                       name=name)}
            tmp = self._file(key) + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump(blob, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, self._file(key))
        except Exception as e:
            logger.warning("AOT cache store failed for %s key=%s: %r",
                           name, key[:12], e)
            return False
        return True


def env_cache() -> AOTCache | None:
    """The cache named by ``$BIGDL_TPU_AOT_CACHE_DIR``, or None."""
    path = os.environ.get(PATH_ENV)
    return AOTCache(path) if path else None


class StepCompiler:
    """The explicit step-construction pipeline both optimizers use:
    per-signature ``lower -> compile -> cache`` with compile_watch
    accounting, replacing implicit jit-on-first-call compilation.

    ``quick_key`` is the caller's cheap per-iteration dispatch key (batch
    shapes/dtypes); the full cache key — abstract signature of ALL
    arguments plus mesh/donation/fingerprint/extra — is only computed on
    a quick-key miss, so steady-state iterations cost one dict probe.
    """

    def __init__(self, jit_fn, *, name: str, cache: AOTCache | None
                 = None, mesh=None, donate_argnums=(), extra=None,
                 watch=None, count_calls: bool = False):
        self.jit_fn = jit_fn
        self.name = name
        # None = follow the environment; False = explicitly off
        self.cache = (None if cache is False
                      else cache if cache is not None else env_cache())
        self.mesh = mesh
        self.donate_argnums = tuple(donate_argnums)
        self.extra = extra
        self._count_calls = count_calls
        self._watch = watch
        self._executables: dict = {}
        self._fp = None

    # -- plumbing ------------------------------------------------------
    def _cw(self):
        if self._watch is None:
            from bigdl_tpu.observability.compile_watch import default_watch
            self._watch = default_watch()
        return self._watch

    def signature(self, args) -> tuple:
        from bigdl_tpu.observability.compile_watch import signature_of
        return signature_of(args)

    def key_for(self, args) -> str:
        if self._fp is None:
            self._fp = fingerprint()
        return cache_key(self.name, self.signature(args),
                         mesh=self.mesh,
                         donate_argnums=self.donate_argnums,
                         extra=self.extra, fp=self._fp)

    # -- the pipeline --------------------------------------------------
    def get(self, quick_key, args):
        """The executable for this iteration's ``quick_key``, building
        it through the cache on first sight. Returns
        ``(compiled, compiled_this_call)``."""
        compiled = self._executables.get(quick_key)
        if compiled is not None:
            if self._count_calls:
                self._cw().note_call(self.name, quick_key)
            return compiled, False
        loaded = False
        if self.cache is not None:
            key = self.key_for(args)
            compiled = self.cache.load(key, name=self.name)
            loaded = compiled is not None
        if compiled is None:
            from bigdl_tpu.observability import trace
            with trace.span("compile step", step=self.name,
                            shape=str(quick_key)):
                compiled = self.jit_fn.lower(*args).compile()
            if self.cache is not None:
                self.cache.store(key, compiled, name=self.name)
        self._executables[quick_key] = compiled
        # compile accounting: a cache LOAD still counts as this name's
        # signature appearing (storm detection keys on signatures, and a
        # load means the signature is new to this process)
        cw = self._cw()
        if self._count_calls:
            cw.note_call(self.name, quick_key)
        else:
            cw.note_call(self.name, (("key", repr(quick_key)),))
        try:
            cw.record_executable(self.name, compiled)
        except Exception:
            pass
        return compiled, not loaded

    def __len__(self):
        return len(self._executables)

    def __contains__(self, quick_key):
        return quick_key in self._executables

"""Measured autotuning over the stack's performance knobs (ROADMAP 4).

TVM-style search (arXiv:1802.04799) scaled down to the knobs this repo
actually has: Pallas tile configurations (flash attention BQ/BK, fused-CE
token/vocab tiles, LRN spatial tiles, maxpool H/N tiles), batch geometry,
and the sharded-update ``bucket_mb``. The search is MEASURED — each
surviving candidate is compiled and timed on the live backend — but
pruned and ordered first by a static cost model so obviously illegal or
VMEM-overflowing candidates never compile:

- ``est_vmem(config)`` estimates a candidate's peak VMEM footprint;
  anything past ``vmem_budget`` is skipped without building (the menu
  comments in ops/pallas/* record real OOMs at exactly these sizes).
- ``seed_stats`` — the per-executable FLOPs/bytes table
  ``observability.compile_watch.executable_stats`` extracts for the
  incumbent configuration — feeds ``est_cost(config, stats)`` so the
  most promising candidates measure first and ``max_candidates`` cuts
  the tail of a bandwidth-dominated search space instead of a random
  subset.

Winners persist to :mod:`tuning.records` keyed by (kernel, abstract
shape signature, device kind); the kernel block pickers consult that
store before their static menus, so one tuning pass feeds the whole
fleet.

HOST-ONLY CONTRACT (jaxlint JX5): jax is imported lazily inside the
measurement helpers only.
"""
from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field

from bigdl_tpu.tuning.records import TuningRecords, default_records

__all__ = ["tune", "TuneResult", "Measurement", "VMEM_BUDGET_BYTES",
           "flash_candidates", "flash_est_vmem", "fused_ce_candidates",
           "fused_ce_est_vmem", "lrn_candidates", "lrn_est_vmem",
           "maxpool_candidates", "bucket_mb_candidates",
           "batch_geometry_candidates", "chunk_records_candidates",
           "tile_divisors",
           "paged_attention_candidates", "paged_attention_est_vmem",
           "step_memory_candidates", "step_memory_est_hbm",
           "pipeline_schedule_candidates", "pipeline_est_hbm"]

logger = logging.getLogger("bigdl_tpu.tuning")

#: per-core VMEM to budget tile temporaries against (16 MB on v4/v5e;
#: the estimate is deliberately conservative — double-buffered inputs
#: plus f32 scratch)
VMEM_BUDGET_BYTES = 16 * (1 << 20)


@dataclass
class Measurement:
    config: dict
    time_s: float | None          # None when skipped/failed
    skipped: str | None = None    # why it never ran (pruned/error)


@dataclass
class TuneResult:
    config: dict                  # the winner
    time_s: float
    baseline_time_s: float | None
    tie: bool                     # winner == baseline config
    measurements: list[Measurement] = field(default_factory=list)
    record_key: str | None = None


def _sync(out) -> None:
    """Force device completion of a candidate run. ``device_get`` is the
    sanctioned real sync (block_until_ready is a no-op through the axon
    tunnel, bench.py measured)."""
    import jax
    leaves = [l for l in jax.tree.leaves(out)
              if hasattr(l, "dtype") and hasattr(l, "shape")]
    if leaves:
        jax.device_get(leaves[0])


def tune(build, candidates, *, key, records: TuningRecords | None = None,
         warmup: int = 1, iters: int = 3, est_vmem=None,
         vmem_budget: int = VMEM_BUDGET_BYTES, seed_stats: dict | None
         = None, est_cost=None, max_candidates: int | None = None,
         baseline: dict | None = None, persist: bool = True
         ) -> TuneResult:
    """Measured search over ``candidates`` (dicts of knob values).

    ``build(config)`` returns a zero-argument callable running the
    workload once at that configuration (its first call may compile —
    compile time is excluded by the ``warmup`` calls). ``key`` is the
    ``(kernel, signature)`` pair the winner persists under.

    Static pruning happens BEFORE ``build``: candidates whose
    ``est_vmem(config)`` exceeds ``vmem_budget`` are skipped unbuilt,
    and when ``est_cost(config, seed_stats)`` is given the survivors
    measure in ascending predicted-cost order with ``max_candidates``
    bounding the measured set (the cut is logged — never silent). A
    candidate whose build/run raises is recorded as skipped with the
    error, not fatal: an illegal tile is a pruning-model gap, not a
    tuning failure.

    ``baseline`` (e.g. the static-menu pick) is measured alongside; a
    winner that IS the baseline is reported as a tie and logged.
    Returns the :class:`TuneResult`; the winner is persisted to
    ``records`` unless ``persist=False``.
    """
    kernel, sig = key
    cands = [dict(c) for c in candidates]
    if baseline is not None and baseline not in cands:
        cands.append(dict(baseline))
    measurements: list[Measurement] = []
    runnable: list[dict] = []
    for c in cands:
        if est_vmem is not None:
            try:
                need = est_vmem(c)
            except Exception as e:
                measurements.append(Measurement(c, None,
                                                f"est_vmem error: {e}"))
                continue
            if need is not None and need > vmem_budget:
                measurements.append(Measurement(
                    c, None, f"pruned: est VMEM {need / 2**20:.1f} MB > "
                             f"budget {vmem_budget / 2**20:.1f} MB"))
                continue
        runnable.append(c)
    if est_cost is not None:
        runnable.sort(key=lambda c: est_cost(c, seed_stats))
    if max_candidates is not None and len(runnable) > max_candidates:
        cut = runnable[max_candidates:]
        # the baseline must always measure, or the tie/beat verdict
        # would compare against nothing
        keep = runnable[:max_candidates]
        if baseline is not None and baseline in cut:
            keep.append(dict(baseline))
            cut = [c for c in cut if c != baseline]
        logger.info("tune(%s): measuring %d of %d candidates "
                    "(cost-model cut dropped %d: %s)", kernel, len(keep),
                    len(runnable), len(cut), cut)
        for c in cut:
            measurements.append(Measurement(c, None,
                                            "pruned: cost-model cut"))
        runnable = keep

    best: Measurement | None = None
    baseline_time = None
    for c in runnable:
        try:
            fn = build(c)
            for _ in range(max(warmup, 1)):   # first call pays compile
                _sync(fn())
            t0 = time.perf_counter()
            out = None
            for _ in range(max(iters, 1)):
                out = fn()
            _sync(out)
            dt = (time.perf_counter() - t0) / max(iters, 1)
        except Exception as e:
            measurements.append(Measurement(c, None,
                                            f"{type(e).__name__}: {e}"))
            logger.info("tune(%s): candidate %s failed: %s", kernel, c, e)
            continue
        m = Measurement(c, dt)
        measurements.append(m)
        if baseline is not None and c == baseline:
            baseline_time = dt
        if best is None or dt < best.time_s:
            best = m
    if best is None:
        raise ValueError(
            f"tune({kernel}): no candidate survived — "
            f"{[m.skipped for m in measurements]}")

    tie = baseline is not None and best.config == baseline
    if tie:
        logger.info("tune(%s, %s): TIE — measured winner equals the "
                    "static default %s (%.3g s)", kernel, sig,
                    best.config, best.time_s)
    elif baseline_time is not None:
        logger.info("tune(%s, %s): %s (%.3g s) beats static %s "
                    "(%.3g s), %.2fx", kernel, sig, best.config,
                    best.time_s, baseline, baseline_time,
                    baseline_time / max(best.time_s, 1e-12))
    rec_key = None
    if persist:
        store = records if records is not None else default_records()
        rec_key = store.record(kernel, sig, best.config,
                               score=best.time_s,
                               meta={"iters": iters,
                                     "measured": sum(
                                         1 for m in measurements
                                         if m.time_s is not None),
                                     "tie_with_static": tie})
    return TuneResult(config=dict(best.config), time_s=best.time_s,
                      baseline_time_s=baseline_time, tie=tie,
                      measurements=measurements, record_key=rec_key)


# ---------------------------------------------------------------------------
# candidate generators + VMEM estimators, one pair per tuned kernel.
# The estimators model the f32 scratch + double-buffered block inputs of
# the actual kernels (ops/pallas/*) — deliberately a slight OVERestimate
# so the prune errs toward skipping a config that might have fit.
# ---------------------------------------------------------------------------

def tile_divisors(n: int, cap: int, floor: int = 128, step: int = 16
                  ) -> list[int]:
    """Tile sizes that legally divide ``n``: multiples of ``step`` (the
    bf16 sublane tile — legal for f32 too) from ``cap`` down to
    ``floor``, largest first."""
    top = min(cap, n)
    return [b for b in range(top - top % step, floor - 1, -step)
            if n % b == 0]


def flash_candidates(sq: int, skv: int, *, q_cap: int = 512,
                     k_cap: int = 1024) -> list[dict]:
    """(BQ, BK) grid for flash attention at (sq, skv): every legal
    divisor pair, menu sizes included."""
    return [{"bq": bq, "bk": bk}
            for bq in tile_divisors(sq, q_cap)
            for bk in tile_divisors(skv, k_cap)]


def flash_est_vmem(d: int, dtype_bytes: int = 2):
    """Forward-kernel footprint at head dim ``d``: s+p f32 tiles
    (bq, bk), f32 acc (bq, d), double-buffered q/k/v blocks."""
    def est(c: dict) -> int:
        bq, bk = c["bq"], c["bk"]
        f32 = 4
        return (2 * bq * bk * f32 + bq * d * f32
                + 2 * (bq * d + 2 * bk * d) * dtype_bytes)
    return est


def fused_ce_candidates(n: int, v: int, *, t_cap: int = 512,
                        v_cap: int = 1024) -> list[dict]:
    return [{"bt": bt, "bv": bv}
            for bt in tile_divisors(n, t_cap)
            for bv in tile_divisors(v, v_cap)]


def fused_ce_est_vmem(d: int, dtype_bytes: int = 2):
    """dW-kernel footprint (the fattest of the three): f32 logits tile
    (bt, bv), f32 dw scratch (bv, d), double-buffered h/w blocks."""
    def est(c: dict) -> int:
        bt, bv = c["bt"], c["bv"]
        f32 = 4
        return (bt * bv * f32 + bv * d * f32
                + 2 * (bt * d + bv * d) * dtype_bytes)
    return est


def lrn_candidates(hw: int) -> list[dict]:
    """Spatial-row tiles for the LRN kernel: powers of two dividing the
    plane (the swept menu was 1..16; >=16 OOMed at C=192, N=256 — the
    estimator prunes those shapes per-geometry instead of globally)."""
    return [{"ht": ht} for ht in (16, 8, 4, 2, 1) if hw % ht == 0]


def lrn_est_vmem(c_dim: int, n: int):
    def est(c: dict) -> int:
        # ~4 live f32 (ht, C, N) temps in the backward (x, s, acc, dx)
        return 4 * c["ht"] * c_dim * n * 4
    return est


def maxpool_candidates(h: int, n: int) -> list[dict]:
    """H-tile / N-tile grid for the maxpool backward kernel."""
    hts = [ht for ht in (8, 4, 2) if h % ht == 0] or [h]
    nts = [nt for nt in (256, 128) if n % nt == 0] or [min(n, 256)]
    return [{"h_t": ht, "n_t": nt} for ht in hts for nt in nts]


def paged_attention_candidates(t: int, g: int, *, bt_cap: int = 8,
                               gp_octaves: int = 2) -> list[dict]:
    """(bt, gp) grid for the paged-attention decode kernel at query
    width ``t`` and group size ``g`` (query heads per kv head): every
    divisor of ``t`` up to ``bt_cap`` crossed with sublane-aligned
    group paddings — more padded rows fatten the score tile (MXU
    utilization at tiny G) at the cost of wasted lanes."""
    bts = [b for b in range(min(bt_cap, t), 0, -1) if t % b == 0]
    gp0 = -(-g // 8) * 8
    gps = [gp0 * (1 << k) for k in range(max(gp_octaves, 1))]
    return [{"bt": bt, "gp": gp} for bt in bts for gp in gps]


def paged_attention_est_vmem(s: int, d: int, dtype_bytes: int = 2):
    """Kernel footprint at page size ``s``, head dim ``d``: f32 score +
    prob tiles (R, S), f32 acc (R, D) + m/l columns, double-buffered
    k/v page blocks and the q block (R = bt * gp rows)."""
    def est(c: dict) -> int:
        r = c["bt"] * c["gp"]
        f32 = 4
        return (2 * r * s * f32 + r * (d + 2) * f32
                + 2 * (2 * s * d + r * d) * dtype_bytes)
    return est


def step_memory_candidates(batch: int, *, policies=None,
                           max_microbatches: int = 8) -> list[dict]:
    """``(remat_policy, num_microbatches)`` grid for the train step's
    memory-for-throughput knobs (optim/remat.py, optim/accumulation.py):
    every known policy crossed with the powers of two dividing ``batch``
    up to ``max_microbatches``. The measured ``tune()`` over these picks
    the fastest step that FITS — more microbatches / heavier remat free
    HBM for a larger per-chip batch at the cost of recompute and scan
    overhead."""
    from bigdl_tpu.optim.remat import known_remat_policies
    if policies is None:
        policies = known_remat_policies()
    ks, k = [], 1
    while k <= min(int(max_microbatches), int(batch)):
        if batch % k == 0:
            ks.append(k)
        k *= 2
    return [{"remat_policy": p, "num_microbatches": k}
            for p in policies for k in ks]


def step_memory_est_hbm(residual_bytes_by_policy: dict,
                        persistent_bytes: int = 0):
    """Static peak-HBM estimator for ``step_memory_candidates``
    configs, from per-policy ``saved_residual_bytes`` measured once at
    k=1 (optim/remat.py): the activation term scales with microbatch
    size (1/k), the persistent term (params/grads/optimizer state) does
    not. Use as ``est_vmem=`` with an HBM budget, or as ``est_cost=``
    to order candidates memory-first."""
    def est(c: dict) -> int:
        rb = residual_bytes_by_policy[c["remat_policy"]]
        return int(persistent_bytes + rb // max(int(
            c.get("num_microbatches", 1)), 1))
    return est


def pipeline_schedule_candidates(batch: int, n_layers: int,
                                 stage_counts=(2, 4), *,
                                 max_microbatches: int = 16,
                                 max_virtual: int = 4) -> list[dict]:
    """``(schedule, num_microbatches, stages, virtual_stages)`` grid for
    the pipelined train step (parallel/pipeline.py): every power-of-two
    microbatch count dividing ``batch`` crossed with the stage counts
    that divide the layer stack, gpipe/1f1b at v=1 plus interleaved
    variants while the chunking stays legal (layers divide S*v,
    microbatches divide S). The measured ``tune()`` over these picks the
    schedule with the smallest real step time; the static estimator
    (:func:`pipeline_est_hbm`) prunes configurations whose activation
    stash cannot fit before anything compiles."""
    out = []
    ms, k = [], 1
    while k <= min(int(max_microbatches), int(batch)):
        if batch % k == 0:
            ms.append(k)
        k *= 2
    for s in stage_counts:
        s = int(s)
        if s < 1 or n_layers % s:
            continue
        for m in ms:
            for sched in ("gpipe", "1f1b"):
                out.append({"schedule": sched, "num_microbatches": m,
                            "stages": s, "virtual_stages": 1})
            v = 2
            while v <= int(max_virtual) and n_layers % (s * v) == 0:
                if m % s == 0:
                    out.append({"schedule": "interleaved_1f1b",
                                "num_microbatches": m, "stages": s,
                                "virtual_stages": v})
                v *= 2
    return out


def pipeline_est_hbm(act_bytes_full_batch: int,
                     persistent_bytes: int = 0):
    """Static per-stage HBM estimator for
    :func:`pipeline_schedule_candidates` configs, built on the existing
    per-stage residual model: the schedule's EXACT activation-stash
    bound (``pipeline_schedule_stats`` — M microbatches for gpipe, ~S
    for 1f1b) times the per-microbatch activation bytes
    (``act_bytes_full_batch`` / M — the k=1 ``saved_residual_bytes``
    term scaled the same way ``step_memory_est_hbm`` scales it), plus
    the per-stage share of the persistent bytes. Use as ``est_vmem=``
    with an HBM budget, or as ``est_cost=`` to order candidates
    memory-first."""
    def est(c: dict) -> int:
        from bigdl_tpu.parallel.pipeline import pipeline_schedule_stats
        m = max(int(c.get("num_microbatches", 1)), 1)
        s = max(int(c.get("stages", 1)), 1)
        st = pipeline_schedule_stats(
            m, s, c.get("schedule", "1f1b"),
            virtual_stages=int(c.get("virtual_stages", 1)))
        per_mb = act_bytes_full_batch // m
        return int(persistent_bytes // s
                   + st["peak_stash_microbatches"] * per_mb)
    return est


def bucket_mb_candidates() -> list[dict]:
    """Sharded-update gradient bucket sizes (optim/sharded_update.py):
    small buckets overlap more of the backward, big buckets amortize
    collective latency — the right point is model- and mesh-dependent."""
    return [{"bucket_mb": mb} for mb in (1.0, 2.0, 4.0, 8.0, 16.0)]


def batch_geometry_candidates(global_batch: int, n_shards: int,
                              *, span: int = 2) -> list[dict]:
    """Per-step batch geometries near ``global_batch`` that keep the
    data-axis divisibility contract: halving/doubling within ``span``
    octaves, shard-divisible only."""
    out = []
    for k in range(-span, span + 1):
        b = int(global_batch * (2.0 ** k))
        if b >= n_shards and b % n_shards == 0:
            out.append({"batch": b})
    return out


def chunk_records_candidates(n_records: int,
                             num_shards: int = 1) -> list[dict]:
    """Record-store chunk sizes (dataset/recordstore.py): small chunks
    shuffle finer and rebalance better across hosts, big chunks amortize
    footer/index overhead and read sequentially. Octave scan filtered so
    every shard owns at least one chunk per pass
    (dataset/distributed.py's assignment precondition)."""
    out = []
    for cr in (64, 128, 256, 512, 1024, 2048):
        n_chunks = (int(n_records) + cr - 1) // cr
        if n_chunks >= max(1, int(num_shards)):
            out.append({"chunk_records": cr})
    return out

"""Autotuning + persistent AOT executable cache (ROADMAP item 4).

Two halves, one goal — a restarting worker reaches full speed in
seconds, not minutes:

- :mod:`tuning.autotuner` / :mod:`tuning.records`: measured search
  (``tune``) over Pallas tile configs, batch geometry and sharded-update
  bucket sizes, pruned by a static VMEM/cost model before anything
  compiles; winners persist to a JSON record store keyed by (kernel,
  abstract-shape signature, device kind) that the kernels' block
  pickers consult before their static menus.
- :mod:`tuning.aot_cache`: the explicit ``lower -> compile -> cache``
  step-construction pipeline (``StepCompiler``) with serialized
  executables (``AOTCache``) keyed by (abstract signature, mesh,
  donation mask, library+device fingerprint), with a fresh-compile
  backstop on any load failure.

See docs/PERFORMANCE.md "Autotuning & AOT executable cache".

HOST-ONLY package (jaxlint JX5): jax is only imported lazily inside
functions that measure or compile.
"""
from bigdl_tpu.tuning.aot_cache import (AOTCache, StepCompiler,
                                        cache_key, fingerprint,
                                        stable_repr)
from bigdl_tpu.tuning.autotuner import (TuneResult, VMEM_BUDGET_BYTES,
                                        batch_geometry_candidates,
                                        bucket_mb_candidates,
                                        flash_candidates,
                                        flash_est_vmem,
                                        fused_ce_candidates,
                                        fused_ce_est_vmem,
                                        lrn_candidates, lrn_est_vmem,
                                        maxpool_candidates,
                                        tile_divisors, tune)
from bigdl_tpu.tuning.records import (TuningRecords, default_records,
                                      device_kind, set_default_records,
                                      signature_str)

__all__ = [
    "AOTCache", "StepCompiler", "cache_key", "fingerprint",
    "stable_repr",
    "TuneResult", "VMEM_BUDGET_BYTES", "tune", "tile_divisors",
    "flash_candidates", "flash_est_vmem", "fused_ce_candidates",
    "fused_ce_est_vmem", "lrn_candidates", "lrn_est_vmem",
    "maxpool_candidates", "bucket_mb_candidates",
    "batch_geometry_candidates",
    "TuningRecords", "default_records", "set_default_records",
    "device_kind", "signature_str",
]

"""Tensor parallelism over the mesh ``model`` axis, the GSPMD way.

The reference has no tensor parallelism (its model lives whole on every
executor). On TPU the idiomatic construction is NOT hand-written
column/row-parallel layers with explicit collectives — it is layout
annotation: store each parameter sharded over the ``model`` axis and let
XLA's SPMD partitioner split the matmuls/convs and insert the collectives
(the "pick a mesh, annotate shardings, let XLA do the rest" recipe).
Math is unchanged by construction; only layout and communication differ.

``shard_params`` classifies a params pytree into per-leaf
``NamedSharding``s:

- Linear-like (out, in) 2-D weights  -> P(axis, None)   (column parallel)
- Conv OIHW 4-D weights              -> P(axis)         (output channels)
- 1-D biases/affine whose length matches a sharded out-dim -> P(axis)
- everything else (BN stats, scalars, indivisible dims) -> replicated

A dim that does not divide the axis size falls back to replicated —
correctness never depends on divisibility.
"""
from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.parallel.engine import get_mesh

__all__ = ["shard_params", "sharding_for_tree_like"]


def _leaf_spec(leaf, n: int, axis: str) -> P:
    shape = getattr(leaf, "shape", ())
    if len(shape) == 2 and shape[0] % n == 0:
        return P(axis, None)          # (out, in) — column parallel
    if len(shape) == 4 and shape[0] % n == 0:
        return P(axis)                # OIHW — shard output channels
    if len(shape) == 1 and shape[0] % n == 0 and shape[0] >= n:
        return P(axis)                # bias/affine along the out dim
    return P()


def shard_params(params, mesh: Mesh | None = None, axis: str = "model"):
    """Per-leaf NamedSharding tree for tensor-parallel parameter layout."""
    mesh = mesh or get_mesh()
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no '{axis}' axis: {mesh.axis_names}")
    n = mesh.shape[axis]
    return jax.tree.map(
        lambda l: NamedSharding(mesh, _leaf_spec(l, n, axis)), params)


def sharding_for_tree_like(tree, params, param_shardings, default):
    """Extend a params sharding tree onto a params-SHAPED subtree holder
    (optimizer state): any top-level value whose tree structure matches
    ``params`` gets ``param_shardings``; everything else ``default``."""
    pstruct = jax.tree.structure(params)
    out = {}
    for key, val in tree.items():
        if jax.tree.structure(val) == pstruct:
            out[key] = param_shardings
        else:
            out[key] = jax.tree.map(lambda _: default, val)
    return out


def shard_optim_state_zero1(opt_state, params, mesh: Mesh | None = None,
                            axis: str = "data", param_shardings=None):
    """ZeRO-1-style layout for optimizer state: params-shaped subtrees
    (momentum, Adagrad accumulators) sharded along dim 0 over the data
    axis, so each replica stores 1/N of them (the reference's per-slice
    SGD-state ownership, DistriOptimizer.scala:231-232). Leaves whose dim
    0 does not divide — or that already carry a tensor-parallel spec in
    ``param_shardings`` — keep that layout instead."""
    mesh = mesh or get_mesh()
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no '{axis}' axis: {mesh.axis_names}")
    n = mesh.shape[axis]
    repl = NamedSharding(mesh, P())

    def leaf_sharding(leaf, existing):
        if existing is not None and existing.spec != P():
            return existing              # TP layout wins where present
        shape = getattr(leaf, "shape", ())
        if shape and shape[0] % n == 0 and shape[0] >= n:
            return NamedSharding(mesh, P(axis))
        return repl

    pstruct = jax.tree.structure(params)
    out = {}
    for key, val in opt_state.items():
        if jax.tree.structure(val) == pstruct:
            if param_shardings is not None:
                out[key] = jax.tree.map(leaf_sharding, val, param_shardings)
            else:
                out[key] = jax.tree.map(
                    lambda l: leaf_sharding(l, None), val)
        else:
            out[key] = jax.tree.map(lambda _: repl, val)
    return out

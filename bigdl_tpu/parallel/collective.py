"""Collective communication primitives over the device mesh.

This is the TPU-native replacement for the reference's hand-rolled
BlockManager communication backend (parameters/AllReduceParameter.scala:53-229
— reduce-scatter of gradient slices + all-gather of weight slices through a
KV store, SURVEY §2.6/§5.8). Here each collective is an XLA op over a named
mesh axis, laid onto ICI (within a slice) or DCN (across slices) by the
compiler; the helpers wrap ``shard_map`` so callers can run collectives
eagerly (outside a jit) or compose them inside one.

The wire-compression parity point: the reference compresses f32 to "fp16" by
truncating to the TOP 16 BITS of the IEEE float (FP16CompressedTensor.scala:
267-275) — that bit pattern IS bfloat16. So ``wire_dtype=jnp.bfloat16``
reproduces the reference's wire format exactly, natively on TPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from bigdl_tpu.parallel.engine import get_mesh

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "ppermute",
           "all_to_all", "psum_tree", "pmean_tree"]


def _wire(x, wire_dtype):
    return x.astype(wire_dtype) if wire_dtype is not None else x


def all_reduce(x, axis: str = "data", mesh: Mesh | None = None, *,
               mean: bool = False, wire_dtype=None):
    """Sum (or mean) ``x`` across ``axis``; every shard gets the result.

    Equivalent of the reference's putGradients+aggregate+getWeights round
    trip collapsed into one ``lax.psum``.
    """
    mesh = mesh or get_mesh()
    orig_dtype = x.dtype

    def body(v):
        v = _wire(v, wire_dtype)
        out = jax.lax.pmean(v, axis) if mean else jax.lax.psum(v, axis)
        return out.astype(orig_dtype)

    spec = P()  # replicated value per shard
    return shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec,
                     check_rep=False)(x)


def psum_tree(tree, axis: str = "data", mesh: Mesh | None = None, *,
              mean: bool = False, wire_dtype=None):
    """all_reduce over every leaf of a pytree (flat-gradient equivalent)."""
    return jax.tree.map(
        lambda v: all_reduce(v, axis, mesh, mean=mean,
                             wire_dtype=wire_dtype), tree)


def pmean_tree(tree, axis: str = "data", mesh: Mesh | None = None, *,
               wire_dtype=None):
    return psum_tree(tree, axis, mesh, mean=True, wire_dtype=wire_dtype)


def all_gather(x, axis: str = "data", mesh: Mesh | None = None,
               concat_axis: int = 0):
    """Each shard contributes its block; all get the concatenation
    (reference AllReduceParameter.getWeights, :134-159)."""
    mesh = mesh or get_mesh()

    def body(v):
        out = jax.lax.all_gather(v, axis, tiled=True)
        if concat_axis != 0:
            out = jnp.moveaxis(out, 0, concat_axis)
        return out

    return shard_map(body, mesh=mesh, in_specs=(P(axis),), out_specs=P(),
                     check_rep=False)(x)


def reduce_scatter(x, axis: str = "data", mesh: Mesh | None = None, *,
                   wire_dtype=None):
    """Sum across shards, each shard keeps its slice of dim 0 (reference
    putGradients + aggregrateGradientPartition, :161-215)."""
    mesh = mesh or get_mesh()
    orig_dtype = x.dtype

    def body(v):
        v = _wire(v, wire_dtype)
        out = jax.lax.psum_scatter(v, axis, scatter_dimension=0, tiled=True)
        return out.astype(orig_dtype)

    return shard_map(body, mesh=mesh, in_specs=(P(),), out_specs=P(axis),
                     check_rep=False)(x)


def ppermute(x, perm, axis: str = "data", mesh: Mesh | None = None):
    """Point-to-point ring shift (ring-attention building block).

    ``perm`` is a list of (src, dst) pairs over the axis indices.
    """
    mesh = mesh or get_mesh()
    return shard_map(
        lambda v: jax.lax.ppermute(v, axis, perm),
        mesh=mesh, in_specs=(P(axis),), out_specs=P(axis),
        check_rep=False)(x)


def all_to_all(x, axis: str = "data", mesh: Mesh | None = None, *,
               split_axis: int = 1, concat_axis: int = 0):
    """Transpose shard ownership between two tensor dims (DeepSpeed-Ulysses
    style sequence<->head exchange)."""
    mesh = mesh or get_mesh()

    def body(v):
        return jax.lax.all_to_all(v, axis, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)

    return shard_map(body, mesh=mesh, in_specs=(P(axis),),
                     out_specs=P(axis), check_rep=False)(x)

"""Collective communication primitives over the device mesh.

This is the TPU-native replacement for the reference's hand-rolled
BlockManager communication backend (parameters/AllReduceParameter.scala:53-229
— reduce-scatter of gradient slices + all-gather of weight slices through a
KV store, SURVEY §2.6/§5.8). Here each collective is an XLA op over a named
mesh axis, laid onto ICI (within a slice) or DCN (across slices) by the
compiler; the helpers wrap ``shard_map`` so callers can run collectives
eagerly (outside a jit) or compose them inside one.

The wire-compression parity point: the reference compresses f32 to "fp16" by
truncating to the TOP 16 BITS of the IEEE float (FP16CompressedTensor.scala:
267-275) — that bit pattern IS bfloat16. So ``wire_dtype=jnp.bfloat16``
reproduces the reference's wire format exactly, natively on TPU.
"""
from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
try:                                   # jax >= 0.8
    from jax import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_rep)
except ImportError:                    # older jax
    from jax.experimental.shard_map import shard_map

from bigdl_tpu.parallel.engine import get_mesh

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "ppermute",
           "all_to_all", "psum_tree", "pmean_tree",
           "process_allgather_pyobj"]


_MAX_PYOBJ_PAYLOAD = 2 ** 31


def _check_payload_size(n_bytes: int) -> None:
    """The size gather rides jax arrays, which truncate int64 to int32
    when x64 is off (the default) — a >= 2 GiB pickle would overflow
    silently and corrupt the unpickle slicing. Refuse loudly instead
    (ADVICE.md)."""
    if n_bytes >= _MAX_PYOBJ_PAYLOAD:
        raise ValueError(
            f"process_allgather_pyobj payload of {n_bytes} bytes "
            f"meets/exceeds the int32 size-gather limit "
            f"({_MAX_PYOBJ_PAYLOAD - 1} bytes) — shard the object "
            "across several gathers")


def process_allgather_pyobj(obj):
    """Gather one arbitrary (picklable) python object per PROCESS; every
    process returns the list ordered by process index.

    The host-side control-plane counterpart to the in-step collectives
    above — the role Spark's driver-side reduce/accumulators played in
    the reference (Metrics.scala:24-27, DistriValidator.scala:29-80).
    COLLECTIVE over the jax.distributed job: every process must call it
    at the same point. Single-process: returns ``[obj]`` without
    touching the backend. Objects differ in size per process, so lengths
    are gathered first and payloads padded to the max."""
    import pickle

    import numpy as np

    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    _check_payload_size(payload.size)
    sizes = multihost_utils.process_allgather(
        np.asarray([payload.size], np.int64))
    buf = np.zeros(int(sizes.max()), np.uint8)
    buf[:payload.size] = payload
    bufs = multihost_utils.process_allgather(buf)
    return [pickle.loads(bufs[p, :int(sizes[p])].tobytes())
            for p in range(bufs.shape[0])]


def _wire(x, wire_dtype):
    return x.astype(wire_dtype) if wire_dtype is not None else x


def _resolve_codec(codec):
    from bigdl_tpu.parameters.compression import get_codec
    return get_codec(codec)


def _compressed_scatter_body(v, axis, n, codec, key, mean):
    """Per-shard body of a wire-compressed reduce-scatter: quantize my
    full contribution per destination slice, exchange int8/uint16
    payloads with ``all_to_all`` (the wire stays at codec width — a
    psum would have to upcast to accumulate), decode the N received
    contributions and sum locally. Returns my f32 slice."""
    rows = v.reshape(n, -1)                  # row j = my payload for shard j
    enc = codec.encode(rows, key)
    got = {k: jax.lax.all_to_all(p if p.ndim > 1 else p[:, None], axis,
                                 split_axis=0, concat_axis=0, tiled=False)
           for k, p in enc.items()}
    got = {k: (p if enc[k].ndim > 1 else p[..., 0]) for k, p in got.items()}
    out = jnp.sum(codec.decode(got), axis=0)
    return out / n if mean else out


def all_reduce(x, axis: str = "data", mesh: Mesh | None = None, *,
               mean: bool = False, wire_dtype=None):
    """Reduce N per-shard contributions across ``axis``.

    ``x`` is the STACK of contributions: leading dim == mesh.shape[axis],
    ``x[i]`` being what shard ``i`` contributes (the eager emulation of N
    parties each calling the collective with their own value). Returns the
    elementwise sum (or mean) of the blocks, shape ``x.shape[1:]``,
    replicated on every shard.

    Equivalent of the reference's putGradients+aggregate+getWeights round
    trip collapsed into one ``lax.psum``. A replicated input with
    ``in_specs=P()`` would make psum count the same value N times — the
    stacked contract keeps the sum honest.
    """
    mesh = mesh or get_mesh()
    n = mesh.shape[axis]
    if x.ndim == 0 or x.shape[0] != n:
        raise ValueError(
            f"all_reduce wants stacked per-shard contributions: leading dim "
            f"{x.shape[0] if x.ndim else '<scalar>'} != mesh axis "
            f"'{axis}' size {n}")
    orig_dtype = x.dtype

    def body(v):
        v = _wire(v[0], wire_dtype)
        out = jax.lax.pmean(v, axis) if mean else jax.lax.psum(v, axis)
        return out.astype(orig_dtype)

    return shard_map(body, mesh=mesh, in_specs=(P(axis),), out_specs=P(),
                     check_rep=False)(x)


def psum_tree(tree, axis: str = "data", mesh: Mesh | None = None, *,
              mean: bool = False, wire_dtype=None):
    """all_reduce over every leaf of a pytree; each leaf carries the stacked
    per-shard leading dim (flat-gradient equivalent)."""
    return jax.tree.map(
        lambda v: all_reduce(v, axis, mesh, mean=mean,
                             wire_dtype=wire_dtype), tree)


def pmean_tree(tree, axis: str = "data", mesh: Mesh | None = None, *,
               wire_dtype=None):
    return psum_tree(tree, axis, mesh, mean=True, wire_dtype=wire_dtype)


def all_gather(x, axis: str = "data", mesh: Mesh | None = None,
               concat_axis: int = 0, *, codec=None):
    """Each shard contributes its block; all get the concatenation
    (reference AllReduceParameter.getWeights, :134-159).

    ``codec`` (a name from ``parameters.compression.KNOWN_CODECS`` or a
    ``WireCodec``) compresses the payload on the wire — the reference's
    FP16 ``getWeights`` is ``codec="bf16"``. Each shard's whole block is
    one codec row (one scale for int8). Requires f32 input."""
    mesh = mesh or get_mesh()
    codec = _resolve_codec(codec)

    def body(v):
        if codec is not None and codec.name != "fp32":
            enc = codec.encode(v.reshape(1, -1))
            got = {k: jax.lax.all_gather(p, axis, tiled=True)
                   for k, p in enc.items()}
            out = codec.decode(got).reshape((-1,) + tuple(v.shape[1:]))
        else:
            out = jax.lax.all_gather(v, axis, tiled=True)
        if concat_axis != 0:
            out = jnp.moveaxis(out, 0, concat_axis)
        return out

    return shard_map(body, mesh=mesh, in_specs=(P(axis),), out_specs=P(),
                     check_rep=False)(x)


def reduce_scatter(x, axis: str = "data", mesh: Mesh | None = None, *,
                   mean: bool = False, wire_dtype=None, codec=None,
                   key=None):
    """Sum N per-shard contributions; each shard keeps its slice (reference
    putGradients + aggregrateGradientPartition, :161-215).

    ``x`` is the stack of contributions, shape ``(N, S, ...)`` with
    ``N == mesh.shape[axis]`` — shard ``i`` contributes ``x[i]``. Returns
    the elementwise sum (or mean), shape ``(S, ...)``, sharded over dim 0
    along ``axis`` (each shard owns ``S/N`` rows).

    ``codec`` compresses the WIRE: each shard quantizes its contribution
    per destination slice, slices ride an ``all_to_all`` at codec width,
    and the owner decodes + sums in f32 (a ``psum_scatter`` would have to
    upcast to accumulate — this construction keeps the payload at wire
    width end to end). ``key`` enables stochastic rounding for codecs
    that support it; requires ``S`` divisible by the axis size and rank-1
    slices."""
    mesh = mesh or get_mesh()
    n = mesh.shape[axis]
    if x.ndim == 0 or x.shape[0] != n:
        raise ValueError(
            f"reduce_scatter wants stacked per-shard contributions: leading "
            f"dim {x.shape[0] if x.ndim else '<scalar>'} != mesh axis "
            f"'{axis}' size {n}")
    orig_dtype = x.dtype
    codec = _resolve_codec(codec)
    if codec is not None and codec.name != "fp32":
        if x.ndim != 2:
            raise ValueError(
                "compressed reduce_scatter wants (N, S) stacked flat "
                f"contributions, got rank {x.ndim}")
        if x.shape[1] % n != 0:
            raise ValueError(
                f"compressed reduce_scatter needs S divisible by the "
                f"axis size: {x.shape[1]} % {n} != 0 (pad first — "
                "AllReduceParameter.put_gradients does)")

        def cbody(v, k):
            return _compressed_scatter_body(v[0], axis, n, codec,
                                            k, mean).astype(orig_dtype)

        if key is None:
            body = lambda v: cbody(v, None)
            return shard_map(body, mesh=mesh, in_specs=(P(axis),),
                             out_specs=P(axis), check_rep=False)(x)
        # distinct stochastic-rounding stream per shard
        body = lambda v, k: cbody(
            v, jax.random.fold_in(k, jax.lax.axis_index(axis)))
        return shard_map(body, mesh=mesh, in_specs=(P(axis), P()),
                         out_specs=P(axis), check_rep=False)(x, key)

    def body(v):
        v = _wire(v[0], wire_dtype)
        out = jax.lax.psum_scatter(v, axis, scatter_dimension=0, tiled=True)
        if mean:
            out = out / n
        return out.astype(orig_dtype)

    return shard_map(body, mesh=mesh, in_specs=(P(axis),), out_specs=P(axis),
                     check_rep=False)(x)


def ppermute(x, perm, axis: str = "data", mesh: Mesh | None = None):
    """Point-to-point ring shift (ring-attention building block).

    ``perm`` is a list of (src, dst) pairs over the axis indices.
    """
    mesh = mesh or get_mesh()
    return shard_map(
        lambda v: jax.lax.ppermute(v, axis, perm),
        mesh=mesh, in_specs=(P(axis),), out_specs=P(axis),
        check_rep=False)(x)


def all_to_all(x, axis: str = "data", mesh: Mesh | None = None, *,
               split_axis: int = 1, concat_axis: int = 0):
    """Transpose shard ownership between two tensor dims (DeepSpeed-Ulysses
    style sequence<->head exchange)."""
    mesh = mesh or get_mesh()

    def body(v):
        return jax.lax.all_to_all(v, axis, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)

    return shard_map(body, mesh=mesh, in_specs=(P(axis),),
                     out_specs=P(axis), check_rep=False)(x)

"""Collective communication primitives over the device mesh.

This is the TPU-native replacement for the reference's hand-rolled
BlockManager communication backend (parameters/AllReduceParameter.scala:53-229
— reduce-scatter of gradient slices + all-gather of weight slices through a
KV store, SURVEY §2.6/§5.8). Here each collective is an XLA op over a named
mesh axis, laid onto ICI (within a slice) or DCN (across slices) by the
compiler; the helpers wrap ``shard_map`` so callers can run collectives
eagerly (outside a jit) or compose them inside one.

The wire-compression parity point: the reference compresses f32 to "fp16" by
truncating to the TOP 16 BITS of the IEEE float (FP16CompressedTensor.scala:
267-275) — that bit pattern IS bfloat16. So ``wire_dtype=jnp.bfloat16``
reproduces the reference's wire format exactly, natively on TPU.
"""
from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
try:                                   # jax >= 0.8
    from jax import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_rep)
except ImportError:                    # older jax
    from jax.experimental.shard_map import shard_map

from bigdl_tpu.parallel.engine import get_mesh

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "ppermute",
           "all_to_all", "psum_tree", "pmean_tree",
           "process_allgather_pyobj"]


_MAX_PYOBJ_PAYLOAD = 2 ** 31


def _check_payload_size(n_bytes: int) -> None:
    """The size gather rides jax arrays, which truncate int64 to int32
    when x64 is off (the default) — a >= 2 GiB pickle would overflow
    silently and corrupt the unpickle slicing. Refuse loudly instead
    (ADVICE.md)."""
    if n_bytes >= _MAX_PYOBJ_PAYLOAD:
        raise ValueError(
            f"process_allgather_pyobj payload of {n_bytes} bytes "
            f"meets/exceeds the int32 size-gather limit "
            f"({_MAX_PYOBJ_PAYLOAD - 1} bytes) — shard the object "
            "across several gathers")


def process_allgather_pyobj(obj):
    """Gather one arbitrary (picklable) python object per PROCESS; every
    process returns the list ordered by process index.

    The host-side control-plane counterpart to the in-step collectives
    above — the role Spark's driver-side reduce/accumulators played in
    the reference (Metrics.scala:24-27, DistriValidator.scala:29-80).
    COLLECTIVE over the jax.distributed job: every process must call it
    at the same point. Single-process: returns ``[obj]`` without
    touching the backend. Objects differ in size per process, so lengths
    are gathered first and payloads padded to the max."""
    import pickle

    import numpy as np

    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    _check_payload_size(payload.size)
    sizes = multihost_utils.process_allgather(
        np.asarray([payload.size], np.int64))
    buf = np.zeros(int(sizes.max()), np.uint8)
    buf[:payload.size] = payload
    bufs = multihost_utils.process_allgather(buf)
    return [pickle.loads(bufs[p, :int(sizes[p])].tobytes())
            for p in range(bufs.shape[0])]


def _wire(x, wire_dtype):
    return x.astype(wire_dtype) if wire_dtype is not None else x


def all_reduce(x, axis: str = "data", mesh: Mesh | None = None, *,
               mean: bool = False, wire_dtype=None):
    """Reduce N per-shard contributions across ``axis``.

    ``x`` is the STACK of contributions: leading dim == mesh.shape[axis],
    ``x[i]`` being what shard ``i`` contributes (the eager emulation of N
    parties each calling the collective with their own value). Returns the
    elementwise sum (or mean) of the blocks, shape ``x.shape[1:]``,
    replicated on every shard.

    Equivalent of the reference's putGradients+aggregate+getWeights round
    trip collapsed into one ``lax.psum``. A replicated input with
    ``in_specs=P()`` would make psum count the same value N times — the
    stacked contract keeps the sum honest.
    """
    mesh = mesh or get_mesh()
    n = mesh.shape[axis]
    if x.ndim == 0 or x.shape[0] != n:
        raise ValueError(
            f"all_reduce wants stacked per-shard contributions: leading dim "
            f"{x.shape[0] if x.ndim else '<scalar>'} != mesh axis "
            f"'{axis}' size {n}")
    orig_dtype = x.dtype

    def body(v):
        v = _wire(v[0], wire_dtype)
        out = jax.lax.pmean(v, axis) if mean else jax.lax.psum(v, axis)
        return out.astype(orig_dtype)

    return shard_map(body, mesh=mesh, in_specs=(P(axis),), out_specs=P(),
                     check_rep=False)(x)


def psum_tree(tree, axis: str = "data", mesh: Mesh | None = None, *,
              mean: bool = False, wire_dtype=None):
    """all_reduce over every leaf of a pytree; each leaf carries the stacked
    per-shard leading dim (flat-gradient equivalent)."""
    return jax.tree.map(
        lambda v: all_reduce(v, axis, mesh, mean=mean,
                             wire_dtype=wire_dtype), tree)


def pmean_tree(tree, axis: str = "data", mesh: Mesh | None = None, *,
               wire_dtype=None):
    return psum_tree(tree, axis, mesh, mean=True, wire_dtype=wire_dtype)


def all_gather(x, axis: str = "data", mesh: Mesh | None = None,
               concat_axis: int = 0):
    """Each shard contributes its block; all get the concatenation
    (reference AllReduceParameter.getWeights, :134-159)."""
    mesh = mesh or get_mesh()

    def body(v):
        out = jax.lax.all_gather(v, axis, tiled=True)
        if concat_axis != 0:
            out = jnp.moveaxis(out, 0, concat_axis)
        return out

    return shard_map(body, mesh=mesh, in_specs=(P(axis),), out_specs=P(),
                     check_rep=False)(x)


def reduce_scatter(x, axis: str = "data", mesh: Mesh | None = None, *,
                   mean: bool = False, wire_dtype=None):
    """Sum N per-shard contributions; each shard keeps its slice (reference
    putGradients + aggregrateGradientPartition, :161-215).

    ``x`` is the stack of contributions, shape ``(N, S, ...)`` with
    ``N == mesh.shape[axis]`` — shard ``i`` contributes ``x[i]``. Returns
    the elementwise sum (or mean), shape ``(S, ...)``, sharded over dim 0
    along ``axis`` (each shard owns ``S/N`` rows).
    """
    mesh = mesh or get_mesh()
    n = mesh.shape[axis]
    if x.ndim == 0 or x.shape[0] != n:
        raise ValueError(
            f"reduce_scatter wants stacked per-shard contributions: leading "
            f"dim {x.shape[0] if x.ndim else '<scalar>'} != mesh axis "
            f"'{axis}' size {n}")
    orig_dtype = x.dtype

    def body(v):
        v = _wire(v[0], wire_dtype)
        out = jax.lax.psum_scatter(v, axis, scatter_dimension=0, tiled=True)
        if mean:
            out = out / n
        return out.astype(orig_dtype)

    return shard_map(body, mesh=mesh, in_specs=(P(axis),), out_specs=P(axis),
                     check_rep=False)(x)


def ppermute(x, perm, axis: str = "data", mesh: Mesh | None = None):
    """Point-to-point ring shift (ring-attention building block).

    ``perm`` is a list of (src, dst) pairs over the axis indices.
    """
    mesh = mesh or get_mesh()
    return shard_map(
        lambda v: jax.lax.ppermute(v, axis, perm),
        mesh=mesh, in_specs=(P(axis),), out_specs=P(axis),
        check_rep=False)(x)


def all_to_all(x, axis: str = "data", mesh: Mesh | None = None, *,
               split_axis: int = 1, concat_axis: int = 0):
    """Transpose shard ownership between two tensor dims (DeepSpeed-Ulysses
    style sequence<->head exchange)."""
    mesh = mesh or get_mesh()

    def body(v):
        return jax.lax.all_to_all(v, axis, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)

    return shard_map(body, mesh=mesh, in_specs=(P(axis),),
                     out_specs=P(axis), check_rep=False)(x)

"""Expert parallelism: mixture-of-experts with all_to_all dispatch.

The reference's closest ancestor is ``MixtureTable`` (nn/MixtureTable.scala
— dense gating over experts that all live everywhere). Expert parallelism
is the TPU-scale version: each mesh shard OWNS one expert's parameters,
tokens are routed top-k by a learned gate (k=1 Switch-style default,
k=2 GShard-style), hop to their experts' devices with one
``all_to_all``, run the expert, and hop back. Capacity-based dispatch
(fixed C slots per expert) keeps every shape static for XLA; overflow
ranks drop, fully-dropped tokens pass through unchanged (standard MoE
practice).

Functional and differentiable end-to-end: the gate receives gradients
through the combine weights, experts through their tokens.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.parallel.collective import shard_map
from bigdl_tpu.parallel.engine import get_mesh

__all__ = ["moe_apply"]


def moe_apply(expert_apply, stacked_expert_params, x, gate_w, *,
              capacity_factor: float = 1.25, axis: str = "model",
              mesh: Mesh | None = None, k: int = 1,
              renormalize: bool = True):
    """Top-k mixture of experts over mesh ``axis`` (one expert per shard).

    - ``expert_apply(expert_params, tokens) -> tokens``: one expert's pure
      function over (n, d) tokens.
    - ``stacked_expert_params``: leaves with leading dim E == axis size
      (expert e's params live on shard e).
    - ``x``: (tokens, d), sharded over ``axis`` (each shard's local
      tokens); ``gate_w``: (d, E) replicated.
    - ``k``: experts per token — 1 (Switch-style, the default) or 2+
      (GShard-style). Ranks claim capacity slots in order (every token's
      first choice before any second choice); a rank whose expert queue
      is full is dropped for that rank only. ``renormalize`` divides the
      k gate probs by their sum (GShard practice; ignored at k=1).

    Returns (y, aux_loss) — y shaped like x (tokens with EVERY rank
    dropped pass through unchanged); aux_loss is the standard
    load-balancing loss over first-choice assignments
    (E * sum_e fraction_e * prob_e).
    """
    mesh = mesh or get_mesh()
    e = mesh.shape[axis]
    n_exp = jax.tree.leaves(stacked_expert_params)[0].shape[0]
    if n_exp != e:
        raise ValueError(f"{n_exp} experts != mesh axis '{axis}' size {e}")
    if x.shape[0] % e:
        raise ValueError(f"tokens {x.shape[0]} not divisible by {e} shards")
    if gate_w.shape[-1] != e:
        raise ValueError(f"gate has {gate_w.shape[-1]} outputs for {e} "
                         "experts")
    if not 1 <= k <= e:
        raise ValueError(f"k={k} must be in [1, {e}]")
    import math
    t_local = x.shape[0] // e
    # true ceil: fractional headroom must survive small tokens-per-expert
    cap = max(1, math.ceil(k * t_local * capacity_factor / e))

    def body(expert_params, xb, gw):
        # xb: (t_local, d) — this shard's tokens
        f32 = jnp.float32
        logits = (xb.astype(f32) @ gw.astype(f32))            # (T, E)
        probs = jax.nn.softmax(logits, axis=-1)
        top_p, top = jax.lax.top_k(probs, k)                  # (T, k)
        if renormalize and k > 1:
            top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)

        # rank-ordered capacity assignment: rank r's queue positions
        # start where ranks < r left each expert's occupancy
        occupied = jnp.zeros((e,), f32)
        ranks = []
        for r in range(k):
            onehot = jax.nn.one_hot(top[:, r], e, dtype=f32)  # (T, E)
            pos = ((jnp.cumsum(onehot, axis=0) - 1.0)
                   + occupied[None, :]) * onehot              # (T, E)
            in_cap = (pos < cap) & (onehot > 0)               # (T, E)
            kept = jnp.any(in_cap, axis=-1)                   # (T,)
            slot = jnp.where(in_cap, pos, 0.0) \
                .sum(axis=-1).astype(jnp.int32)
            occupied = occupied + jnp.sum(
                jnp.where(in_cap, 1.0, 0.0), axis=0)
            ranks.append((onehot, kept, slot))

        # dispatch tensor (E, C, d): rank r of token t -> slot
        # (top[t, r], slot_r[t]); ranks target distinct slots so the
        # scatter-adds never collide
        disp = jnp.zeros((e, cap, xb.shape[1]), xb.dtype)
        for r, (_, kept, slot) in enumerate(ranks):
            disp = disp.at[top[:, r], slot].add(
                jnp.where(kept[:, None], xb, 0).astype(xb.dtype))

        # to experts: all_to_all over the expert dim — shard i receives
        # (E, C, d) where dim 0 is the SOURCE shard, all for expert i
        recv = jax.lax.all_to_all(disp, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
        yexp = expert_apply(
            jax.tree.map(lambda l: l[0], expert_params),
            recv.reshape(e * cap, xb.shape[1]))
        # back to sources (inverse all_to_all)
        back = jax.lax.all_to_all(yexp.reshape(e, cap, xb.shape[1]),
                                  axis, split_axis=0, concat_axis=0,
                                  tiled=True)

        # combine: sum each kept rank's expert output weighted by its
        # gate prob; tokens with every rank dropped pass through
        y = jnp.zeros(xb.shape, f32)
        kept_any = jnp.zeros((xb.shape[0],), bool)
        for r, (_, kept, slot) in enumerate(ranks):
            gathered = back[top[:, r], slot]                  # (T, d)
            y = y + jnp.where(kept[:, None],
                              gathered.astype(f32)
                              * top_p[:, r][:, None], 0.0)
            kept_any = kept_any | kept
        y = jnp.where(kept_any[:, None], y, xb.astype(f32)) \
            .astype(xb.dtype)

        # load-balancing loss (Shazeer-style, over first choices):
        # E * sum_e f_e * p_e
        frac = jnp.mean(ranks[0][0], axis=0)
        mean_p = jnp.mean(probs, axis=0)
        aux = jnp.sum(frac * mean_p) * e
        aux = jax.lax.pmean(aux, axis)
        return y, aux

    pspec = jax.tree.map(lambda _: P(axis), stacked_expert_params)
    y, aux = shard_map(
        body, mesh=mesh,
        in_specs=(pspec, P(axis), P()),
        out_specs=(P(axis), P()),
        check_rep=False)(stacked_expert_params, x, gate_w)
    return y, aux

"""Expert parallelism: mixture-of-experts with all_to_all dispatch.

The reference's closest ancestor is ``MixtureTable`` (nn/MixtureTable.scala
— dense gating over experts that all live everywhere). Expert parallelism
is the TPU-scale version: each mesh shard OWNS one expert's parameters,
tokens are routed top-k by a learned gate (k=1 Switch-style default,
k=2 GShard-style), hop to their experts' devices with one
``all_to_all``, run the expert, and hop back. Capacity-based dispatch
(fixed C slots per expert) keeps every shape static for XLA; overflow
ranks drop, fully-dropped tokens pass through unchanged (standard MoE
practice).

Functional and differentiable end-to-end: the gate receives gradients
through the combine weights, experts through their tokens.

Production wiring (ISSUE 11): :class:`MoE` is the layer a ``Sequential``
model drops in (built-in two-layer FFN experts, learned gate, the
load-balancing aux loss and the dispatch telemetry carried in module
STATE so they ride the train step without extra host syncs), and
``DistriOptimizer.set_expert_parallel()`` threads the aux loss into the
training objective and publishes the drop/overflow/imbalance counters to
the metric registry at epoch boundaries (one batched ``jax.device_get``
per epoch — never a per-step sync; see docs/PERFORMANCE.md).

Combine-weight semantics after capacity drops: the k gate probabilities
renormalize over the KEPT ranks only. A dropped second choice used to
leave the first choice's weight at p1/(p1+p2) — every affected token's
output was silently scaled down by the dropped rank's share, biasing the
combine toward underweighted outputs (ISSUE 11 satellite; pinned in
tests/test_expert_parallel.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.parallel.collective import shard_map
from bigdl_tpu.parallel.engine import get_mesh

__all__ = ["moe_apply", "MoE", "moe_aux_total", "moe_state_stats",
           "publish_moe_metrics"]

#: module-state keys the MoE layer maintains (floats — they survive the
#: gradient-accumulation scan's inexact-leaf averaging)
MOE_STATE_KEYS = ("moe_aux", "moe_dropped_rank_frac",
                  "moe_dropped_token_frac", "moe_overflow_tokens",
                  "moe_load_imbalance")


def moe_apply(expert_apply, stacked_expert_params, x, gate_w, *,
              capacity_factor: float = 1.25, axis: str = "model",
              mesh: Mesh | None = None, k: int = 1,
              renormalize: bool = True, with_stats: bool = False):
    """Top-k mixture of experts over mesh ``axis`` (one expert per shard).

    - ``expert_apply(expert_params, tokens) -> tokens``: one expert's pure
      function over (n, d) tokens.
    - ``stacked_expert_params``: leaves with leading dim E == axis size
      (expert e's params live on shard e).
    - ``x``: (tokens, d), sharded over ``axis`` (each shard's local
      tokens); ``gate_w``: (d, E) replicated.
    - ``k``: experts per token — 1 (Switch-style, the default) or 2+
      (GShard-style). Ranks claim capacity slots in order (every token's
      first choice before any second choice); a rank whose expert queue
      is full is dropped for that rank only. ``renormalize`` divides the
      gate probs of the ranks that were actually KEPT by their sum
      (post-drop renormalization — a dropped rank's share is
      redistributed to the surviving ranks instead of silently shrinking
      the output; ignored at k=1).

    Returns ``(y, aux_loss)`` — y shaped like x (tokens with EVERY rank
    dropped pass through unchanged); aux_loss is the standard
    load-balancing loss over first-choice assignments
    (E * sum_e fraction_e * prob_e). ``with_stats=True`` returns
    ``(y, aux_loss, stats)`` where ``stats`` holds the dispatch
    telemetry, reduced across shards: ``dropped_rank_frac`` (rank
    assignments lost to capacity), ``dropped_token_frac`` (tokens that
    lost EVERY rank and passed through), ``overflow_tokens`` (total
    demand beyond capacity), and ``load_imbalance`` (max over experts of
    first-choice fraction x E; 1.0 = perfectly balanced).
    """
    mesh = mesh or get_mesh()
    e = mesh.shape[axis]
    n_exp = jax.tree.leaves(stacked_expert_params)[0].shape[0]
    if n_exp != e:
        raise ValueError(f"{n_exp} experts != mesh axis '{axis}' size {e}")
    if x.shape[0] % e:
        raise ValueError(f"tokens {x.shape[0]} not divisible by {e} shards")
    if gate_w.shape[-1] != e:
        raise ValueError(f"gate has {gate_w.shape[-1]} outputs for {e} "
                         "experts")
    if not 1 <= k <= e:
        raise ValueError(f"k={k} must be in [1, {e}]")
    import math
    t_local = x.shape[0] // e
    # true ceil: fractional headroom must survive small tokens-per-expert
    cap = max(1, math.ceil(k * t_local * capacity_factor / e))

    def body(expert_params, xb, gw):
        # xb: (t_local, d) — this shard's tokens
        f32 = jnp.float32
        logits = (xb.astype(f32) @ gw.astype(f32))            # (T, E)
        probs = jax.nn.softmax(logits, axis=-1)
        top_p, top = jax.lax.top_k(probs, k)                  # (T, k)

        # rank-ordered capacity assignment: rank r's queue positions
        # start where ranks < r left each expert's occupancy
        occupied = jnp.zeros((e,), f32)
        ranks = []
        for r in range(k):
            onehot = jax.nn.one_hot(top[:, r], e, dtype=f32)  # (T, E)
            pos = ((jnp.cumsum(onehot, axis=0) - 1.0)
                   + occupied[None, :]) * onehot              # (T, E)
            in_cap = (pos < cap) & (onehot > 0)               # (T, E)
            kept = jnp.any(in_cap, axis=-1)                   # (T,)
            slot = jnp.where(in_cap, pos, 0.0) \
                .sum(axis=-1).astype(jnp.int32)
            occupied = occupied + jnp.sum(
                jnp.where(in_cap, 1.0, 0.0), axis=0)
            ranks.append((onehot, kept, slot))

        if renormalize and k > 1:
            # post-drop renormalization: only the ranks that actually
            # made it into capacity share the combine weight (ISSUE 11
            # satellite — dividing by the pre-drop sum left a dropped
            # second choice's share subtracted from the output)
            kept_w = jnp.stack([kept for _, kept, _ in ranks],
                               axis=1).astype(f32)            # (T, k)
            denom = jnp.sum(top_p * kept_w, axis=-1, keepdims=True)
            top_p = top_p / jnp.maximum(denom, 1e-9)

        # dispatch tensor (E, C, d): rank r of token t -> slot
        # (top[t, r], slot_r[t]); ranks target distinct slots so the
        # scatter-adds never collide
        disp = jnp.zeros((e, cap, xb.shape[1]), xb.dtype)
        for r, (_, kept, slot) in enumerate(ranks):
            disp = disp.at[top[:, r], slot].add(
                jnp.where(kept[:, None], xb, 0).astype(xb.dtype))

        # to experts: all_to_all over the expert dim — shard i receives
        # (E, C, d) where dim 0 is the SOURCE shard, all for expert i
        recv = jax.lax.all_to_all(disp, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
        yexp = expert_apply(
            jax.tree.map(lambda l: l[0], expert_params),
            recv.reshape(e * cap, xb.shape[1]))
        # back to sources (inverse all_to_all)
        back = jax.lax.all_to_all(yexp.reshape(e, cap, xb.shape[1]),
                                  axis, split_axis=0, concat_axis=0,
                                  tiled=True)

        # combine: sum each kept rank's expert output weighted by its
        # gate prob; tokens with every rank dropped pass through
        y = jnp.zeros(xb.shape, f32)
        kept_any = jnp.zeros((xb.shape[0],), bool)
        for r, (_, kept, slot) in enumerate(ranks):
            gathered = back[top[:, r], slot]                  # (T, d)
            y = y + jnp.where(kept[:, None],
                              gathered.astype(f32)
                              * top_p[:, r][:, None], 0.0)
            kept_any = kept_any | kept
        y = jnp.where(kept_any[:, None], y, xb.astype(f32)) \
            .astype(xb.dtype)

        # load-balancing loss (Shazeer-style, over first choices):
        # E * sum_e f_e * p_e
        frac = jnp.mean(ranks[0][0], axis=0)
        mean_p = jnp.mean(probs, axis=0)
        aux = jnp.sum(frac * mean_p) * e
        aux = jax.lax.pmean(aux, axis)

        # dispatch telemetry, reduced across shards (stop_gradient —
        # observational, never part of the objective)
        kept_total = sum(jnp.sum(kept.astype(f32))
                         for _, kept, _ in ranks)
        demand = sum(jnp.sum(oh, axis=0) for oh, _, _ in ranks)  # (E,)
        demand = jax.lax.psum(demand, axis)
        n_tok = jax.lax.psum(jnp.asarray(float(t_local), f32), axis)
        stats = {
            "dropped_rank_frac":
                1.0 - jax.lax.psum(kept_total, axis) / (n_tok * k),
            "dropped_token_frac":
                jax.lax.psum(jnp.sum(1.0 - kept_any.astype(f32)),
                             axis) / n_tok,
            "overflow_tokens":
                jnp.sum(jnp.maximum(demand - cap * e, 0.0)),
            "load_imbalance":
                jnp.max(jax.lax.pmean(frac, axis)) * e,
        }
        stats = jax.tree.map(jax.lax.stop_gradient, stats)
        return y, aux, stats

    pspec = jax.tree.map(lambda _: P(axis), stacked_expert_params)
    y, aux, stats = shard_map(
        body, mesh=mesh,
        in_specs=(pspec, P(axis), P()),
        out_specs=(P(axis), P(), {k_: P() for k_ in
                                  ("dropped_rank_frac",
                                   "dropped_token_frac",
                                   "overflow_tokens",
                                   "load_imbalance")}),
        check_rep=False)(stacked_expert_params, x, gate_w)
    if with_stats:
        return y, aux, stats
    return y, aux


from bigdl_tpu.nn.module import Module as _Module  # noqa: E402


class MoE(_Module):
    """Mixture-of-experts layer for ``Sequential`` models: built-in
    two-layer tanh FFN experts (``d -> hidden -> d``), a learned gate,
    top-k expert-parallel dispatch over the given mesh axis.

    The load-balancing aux loss and the dispatch telemetry ride the
    module STATE (``moe_aux`` etc.) — ``set_expert_parallel()`` on the
    optimizer adds the aux term to the training objective and publishes
    the telemetry to the metric registry at epoch boundaries. The state
    leaves are floats, so the gradient-accumulation scan's
    inexact-leaf averaging applies to them like any batch statistic.
    """

    def __init__(self, d_model: int, d_hidden: int, num_experts: int, *,
                 k: int = 1, capacity_factor: float = 1.25,
                 axis: str = "expert", renormalize: bool = True,
                 mesh: Mesh | None = None):
        super().__init__()
        self.d_model = int(d_model)
        self.d_hidden = int(d_hidden)
        self.num_experts = int(num_experts)
        self.k = int(k)
        self.capacity_factor = float(capacity_factor)
        self.axis = axis
        self.renormalize = bool(renormalize)
        self._mesh = mesh

    def init(self, rng):
        import numpy as np
        kg, k1, k2 = jax.random.split(rng, 3)
        e, d, h = self.num_experts, self.d_model, self.d_hidden
        return {
            "gate": (jax.random.normal(kg, (d, e), jnp.float32)
                     / np.sqrt(d)),
            "experts": {
                "w1": (jax.random.normal(k1, (e, d, h), jnp.float32)
                       / np.sqrt(d)),
                "b1": jnp.zeros((e, h), jnp.float32),
                "w2": (jax.random.normal(k2, (e, h, d), jnp.float32)
                       / np.sqrt(h)),
                "b2": jnp.zeros((e, d), jnp.float32),
            },
        }

    def init_state(self):
        return {key: jnp.zeros((), jnp.float32)
                for key in MOE_STATE_KEYS}

    @staticmethod
    def _expert_apply(p, tokens):
        h = jnp.tanh(tokens @ p["w1"] + p["b1"])
        return h @ p["w2"] + p["b2"]

    def apply(self, params, state, x, *, training=False, rng=None):
        d = x.shape[-1]
        if d != self.d_model:
            raise ValueError(f"MoE built for d_model={self.d_model}, "
                             f"got feature dim {d}")
        tokens = x.reshape(-1, d)
        y, aux, stats = moe_apply(
            self._expert_apply, params["experts"], tokens,
            params["gate"], k=self.k,
            capacity_factor=self.capacity_factor, axis=self.axis,
            mesh=self._mesh or get_mesh(),
            renormalize=self.renormalize, with_stats=True)
        new_state = {"moe_aux": aux}
        for key in MOE_STATE_KEYS:
            short = key[len("moe_"):]
            if short in stats:
                new_state[key] = stats[short].astype(jnp.float32)
        return y.reshape(x.shape), new_state

    def __repr__(self):
        return (f"MoE(d{self.d_model}x{self.d_hidden}, "
                f"E={self.num_experts}, k={self.k}, "
                f"cf={self.capacity_factor}, axis={self.axis!r})")


def moe_aux_total(mstate):
    """Sum of every MoE layer's load-balancing aux loss in a module
    state tree (traced — this is the term ``set_expert_parallel`` folds
    into the training objective; gradients flow to the gates through
    it). Zero when the model carries no MoE layers."""
    total = jnp.zeros((), jnp.float32)

    def walk(tree):
        nonlocal total
        if isinstance(tree, dict):
            if "moe_aux" in tree:
                total = total + tree["moe_aux"]
                return
            for sub in tree.values():
                walk(sub)

    walk(mstate)
    return total


def moe_state_stats(mstate) -> dict:
    """Walk a module-state tree for MoE layer states and return
    ``{path: {stat: device array}}`` — one ``jax.device_get`` away from
    host values (the caller batches the readback)."""
    found = {}

    def walk(tree, path):
        if isinstance(tree, dict):
            if "moe_aux" in tree:
                found["/".join(path) or "moe"] = {
                    key: tree[key] for key in MOE_STATE_KEYS
                    if key in tree}
                return
            for key, sub in tree.items():
                walk(sub, path + [str(key)])

    walk(mstate, [])
    return found


def publish_moe_metrics(mstate, registry=None) -> dict:
    """Publish every MoE layer's dispatch telemetry from a module-state
    tree to the metric registry (gauges labeled by layer path; the
    ``moe_dropped_tokens_total``-style exposition names
    docs/OBSERVABILITY.md documents). ONE batched ``jax.device_get`` for
    all layers — call at epoch boundaries or drain points, never
    per step. Returns ``{layer: {stat: float}}``."""
    if registry is None:
        from bigdl_tpu.observability.registry import default_registry
        registry = default_registry()
    staged = moe_state_stats(mstate)
    if not staged:
        return {}
    host = jax.device_get(staged)
    for layer, stats in host.items():
        for key, val in stats.items():
            registry.gauge(
                key, "MoE dispatch telemetry (parallel/expert.py)",
                labelnames=("layer",)).set(float(val), layer=layer)
    return {layer: {key: float(val) for key, val in stats.items()}
            for layer, stats in host.items()}

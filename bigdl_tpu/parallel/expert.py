"""Expert parallelism: mixture-of-experts with all_to_all dispatch.

The reference's closest ancestor is ``MixtureTable`` (nn/MixtureTable.scala
— dense gating over experts that all live everywhere). Expert parallelism
is the TPU-scale version: each mesh shard OWNS one expert's parameters,
tokens are routed top-1 by a learned gate, hop to their expert's device
with one ``all_to_all``, run the expert, and hop back. Capacity-based
dispatch (fixed C slots per expert) keeps every shape static for XLA;
overflow tokens pass through unchanged (standard MoE practice).

Functional and differentiable end-to-end: the gate receives gradients
through the combine weights, experts through their tokens.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.parallel.collective import shard_map
from bigdl_tpu.parallel.engine import get_mesh

__all__ = ["moe_apply"]


def moe_apply(expert_apply, stacked_expert_params, x, gate_w, *,
              capacity_factor: float = 1.25, axis: str = "model",
              mesh: Mesh | None = None):
    """Top-1 mixture of experts over mesh ``axis`` (one expert per shard).

    - ``expert_apply(expert_params, tokens) -> tokens``: one expert's pure
      function over (n, d) tokens.
    - ``stacked_expert_params``: leaves with leading dim E == axis size
      (expert e's params live on shard e).
    - ``x``: (tokens, d), sharded over ``axis`` (each shard's local
      tokens); ``gate_w``: (d, E) replicated.

    Returns (y, aux_loss) — y shaped like x; aux_loss is the standard
    load-balancing loss (mean_e fraction_e * prob_e * E).
    """
    mesh = mesh or get_mesh()
    e = mesh.shape[axis]
    n_exp = jax.tree.leaves(stacked_expert_params)[0].shape[0]
    if n_exp != e:
        raise ValueError(f"{n_exp} experts != mesh axis '{axis}' size {e}")
    if x.shape[0] % e:
        raise ValueError(f"tokens {x.shape[0]} not divisible by {e} shards")
    if gate_w.shape[-1] != e:
        raise ValueError(f"gate has {gate_w.shape[-1]} outputs for {e} "
                         "experts")
    import math
    t_local = x.shape[0] // e
    # true ceil: fractional headroom must survive small tokens-per-expert
    cap = max(1, math.ceil(t_local * capacity_factor / e))

    def body(expert_params, xb, gw):
        # xb: (t_local, d) — this shard's tokens
        f32 = jnp.float32
        logits = (xb.astype(f32) @ gw.astype(f32))            # (T, E)
        probs = jax.nn.softmax(logits, axis=-1)
        top = jnp.argmax(probs, axis=-1)                      # (T,)
        top_p = jnp.take_along_axis(probs, top[:, None], 1)[:, 0]

        # position of each token within its expert's queue
        onehot = jax.nn.one_hot(top, e, dtype=f32)            # (T, E)
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot     # (T, E)
        in_cap = (pos < cap) & (onehot > 0)                   # (T, E)
        kept = jnp.any(in_cap, axis=-1)                       # (T,)

        # dispatch tensor (E, C, d): token t -> slot (top_t, pos_t)
        slot = jnp.where(in_cap, pos, 0.0).sum(axis=-1).astype(jnp.int32)
        disp = jnp.zeros((e, cap, xb.shape[1]), xb.dtype)
        disp = disp.at[top, slot].add(
            jnp.where(kept[:, None], xb, 0).astype(xb.dtype))

        # to experts: all_to_all over the expert dim — shard i receives
        # (E, C, d) where dim 0 is the SOURCE shard, all for expert i
        recv = jax.lax.all_to_all(disp, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
        yexp = expert_apply(
            jax.tree.map(lambda l: l[0], expert_params),
            recv.reshape(e * cap, xb.shape[1]))
        # back to sources (inverse all_to_all)
        back = jax.lax.all_to_all(yexp.reshape(e, cap, xb.shape[1]),
                                  axis, split_axis=0, concat_axis=0,
                                  tiled=True)

        # combine: gather each kept token's slot, weight by its gate prob;
        # overflow tokens pass through
        gathered = back[top, slot]                            # (T, d)
        y = jnp.where(kept[:, None],
                      gathered.astype(f32) * top_p[:, None],
                      xb.astype(f32)).astype(xb.dtype)

        # load-balancing loss (Shazeer-style): E * sum_e f_e * p_e
        frac = jnp.mean(onehot, axis=0)
        mean_p = jnp.mean(probs, axis=0)
        aux = jnp.sum(frac * mean_p) * e
        aux = jax.lax.pmean(aux, axis)
        return y, aux

    pspec = jax.tree.map(lambda _: P(axis), stacked_expert_params)
    y, aux = shard_map(
        body, mesh=mesh,
        in_specs=(pspec, P(axis), P()),
        out_specs=(P(axis), P()),
        check_rep=False)(stacked_expert_params, x, gate_w)
    return y, aux

"""Collective-bandwidth accounting and microbenchmark.

The second BASELINE.json metric is "DistriOptimizer allreduce GB/s". The
reference instruments its aggregation path end-to-end — put/get-gradient
phase timers around the BlockManager reduce-scatter/all-gather
(parameters/AllReduceParameter.scala:134-228, phase metrics at
optim/DistriOptimizer.scala:113-117,172-174,211). Under XLA the gradient
allreduce fuses INTO the compiled step, so the equivalent instrumentation
is:

1. :func:`collective_bytes` — static accounting: parse the compiled step's
   HLO for collective ops and report logical bytes plus the per-chip wire
   bytes a ring schedule moves (all-reduce: 2B(N-1)/N send+recv per chip).
   DistriOptimizer records these in its Metrics every run.
2. :func:`allreduce_bench` — a timed psum microbenchmark at a chosen size
   (default: the Inception-v1 flat gradient, ~13M params) over the mesh's
   ``data`` axis. Reports algorithmic bandwidth (logical bytes / time) and
   bus bandwidth (wire bytes / time — the number NCCL-style harnesses
   quote). On the 8-virtual-CPU-device mesh it validates shape/compile so
   a pod run is one command:

       python -m bigdl_tpu.parallel.collective_bench --sizeMB 54

Cross-check: on one real chip the data axis is 1 and no collective is
emitted — both paths report zero collectives rather than a fake number.
"""
from __future__ import annotations

import re
import time

import numpy as np

__all__ = ["collective_bytes", "allreduce_bench"]

_DT_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "f64": 8,
             "s64": 8, "u64": 8, "s16": 2, "u16": 2, "s8": 1, "u8": 1,
             "pred": 1}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")

# per-chip wire traffic of a ring schedule, as a multiple of the logical
# payload B over N participants (send+recv counted once — the number a
# bus-bandwidth benchmark divides by)
_WIRE_FACTOR = {
    "all-reduce": lambda n: 2.0 * (n - 1) / n,
    "all-gather": lambda n: (n - 1) / n,
    "reduce-scatter": lambda n: (n - 1) / n,
    "collective-permute": lambda n: 1.0,
    "all-to-all": lambda n: (n - 1) / n,
}


def _element_bytes(shape_str: str) -> list[int]:
    out = []
    for m in re.finditer(r"(\w+)\[([\d,]*)\]", shape_str):
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        out.append(n * _DT_BYTES.get(m.group(1), 4))
    return out


def _payload_bytes(shape_str: str, async_start: bool) -> int:
    """Collective payload from an instruction's result shape.

    Async ``-start`` ops carry a tuple of (operand(s), result, ...);
    summing it double-counts the payload (all-reduce-start holds two
    full-size copies). The LARGEST element is the right basis for every
    kind: all-reduce operand==result, all-gather's output and
    reduce-scatter's input are the wire-formula operands and are the
    biggest members."""
    elems = _element_bytes(shape_str)
    if not elems:
        return 0
    return max(elems) if async_start else sum(elems)


def _group_size(line: str, default: int) -> int:
    # replica_groups={{0,1,2,3}} or replica_groups=[2,4]<=[8] forms
    m = re.search(r"replica_groups=\{\{([^}]*)\}", line)
    if m:
        return len([t for t in m.group(1).split(",") if t.strip() != ""])
    m = re.search(r"replica_groups=\[(\d+),(\d+)\]", line)
    if m:
        return int(m.group(2))
    return default


def collective_bytes(hlo_text: str, n_devices: int) -> dict:
    """Account every collective in an optimized HLO module.

    Returns ``{"ops": count, "logical_bytes": B, "wire_bytes_per_chip": W,
    "by_kind": {kind: [count, logical_bytes]}}``. ``start`` variants
    (async collectives) are counted once; ``done`` halves are skipped.
    """
    ops = 0
    logical = 0.0
    wire = 0.0
    by_kind: dict[str, list] = {}
    for line in hlo_text.splitlines():
        ls = line.strip()
        m = re.match(
            r"(?:ROOT\s+)?%?[\w.\-]+\s*=\s*((?:\([^)]*\)|[\w\[\],{}: ()]+?))"
            r"\s+([\w\-]+)\(", ls)
        if not m:
            continue
        op = m.group(2)
        base = op.removesuffix("-start")
        if base not in _COLLECTIVES or op.endswith("-done"):
            continue
        b = _payload_bytes(m.group(1), op.endswith("-start"))
        n = max(_group_size(ls, n_devices), 1)
        ops += 1
        logical += b
        wire += b * _WIRE_FACTOR[base](n)
        k = by_kind.setdefault(base, [0, 0.0])
        k[0] += 1
        k[1] += b
    return {"ops": ops, "logical_bytes": logical,
            "wire_bytes_per_chip": wire, "by_kind": by_kind}


def allreduce_bench(size_mb: float = 54.0, dtype="float32",
                    iters: int = 20, warmup: int = 3, mesh=None,
                    axis: str = "data") -> dict:
    """Timed gradient-sized allreduce over a mesh axis.

    Every device contributes its own distinct buffer (as in sync-SGD) and
    receives the sum — a ``lax.psum`` under ``shard_map``, the exact
    collective DistriOptimizer's backward emits. Default size is the
    Inception-v1 flat f32 gradient (BASELINE.md headline config).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from bigdl_tpu.parallel.engine import get_mesh

    mesh = mesh or get_mesh()
    n = int(mesh.shape[axis])
    dtype = jnp.dtype(dtype)
    length = max(int(size_mb * 1e6 / dtype.itemsize), 1)
    # pad to lanes so the wire payload is the intended size
    length = -(-length // 128) * 128
    host = np.random.default_rng(0)
    x = jnp.asarray(
        host.standard_normal((n, length)).astype(np.float32)).astype(dtype)
    x = jax.device_put(x, NamedSharding(mesh, P(axis)))

    @jax.jit
    def step(x):
        def block(xs):           # xs: (1, length) — this device's gradient
            return jax.lax.psum(xs, axis)

        return shard_map(block, mesh=mesh, in_specs=P(axis),
                         out_specs=P(axis))(x)

    out = step(x)
    jax.block_until_ready(out)
    for _ in range(warmup):
        out = step(x)
    np.asarray(jax.tree.leaves(out)[0][0, 0])   # device sync (axon tunnel)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(x)
    np.asarray(jax.tree.leaves(out)[0][0, 0])
    dt = (time.perf_counter() - t0) / iters

    logical = length * dtype.itemsize
    wire = logical * _WIRE_FACTOR["all-reduce"](n) if n > 1 else 0.0
    out = {
        "metric": "allreduce_bus_bandwidth",
        "devices": n,
        "payload_mb": round(logical / 1e6, 3),
        "dtype": str(dtype),
        "time_ms": round(dt * 1e3, 4),
        "alg_gbps": round(logical / dt / 1e9, 3),
        "bus_gbps": round(wire / dt / 1e9, 3),
        "unit": "GB/s",
    }
    # compile/memory telemetry of the benchmarked executable — the
    # lower().compile() is a cache hit after the timed loop above
    from bigdl_tpu.observability import compile_watch
    try:
        compile_watch.record_executable(
            "collective_bench_allreduce", step.lower(x).compile())
    except Exception:               # telemetry must never fail a bench
        pass

    # export through the process-wide registry so the microbenchmark
    # lands on the same Prometheus/JSON surface as training metrics
    from bigdl_tpu.observability.registry import default_registry
    reg = default_registry()
    lbl = {"dtype": str(dtype), "devices": str(n)}
    names = ("dtype", "devices")
    reg.gauge("collective_bench_alg_gbps",
              "allreduce algorithmic bandwidth (logical bytes / time)",
              labelnames=names).set(out["alg_gbps"], **lbl)
    reg.gauge("collective_bench_bus_gbps",
              "allreduce bus bandwidth (ring wire bytes / time)",
              labelnames=names).set(out["bus_gbps"], **lbl)
    reg.gauge("collective_bench_time_ms",
              "allreduce mean iteration wall clock",
              labelnames=names).set(out["time_ms"], **lbl)
    return out


def main(argv=None):
    import argparse
    import json

    p = argparse.ArgumentParser(
        description="Gradient-allreduce bandwidth microbenchmark "
                    "(BASELINE.json second metric)")
    p.add_argument("--sizeMB", type=float, default=54.0,
                   help="payload size (54 = Inception-v1 f32 flat grad)")
    p.add_argument("--dtype", default="float32",
                   help="payload dtype (bfloat16 = the bf16-wire path)")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--dataParallel", type=int, default=None,
                   help="mesh size (default: all visible devices)")
    args = p.parse_args(argv)

    import os
    if args.dataParallel:
        # honor a device-count request on hosts where the runtime pinned a
        # single chip: fall back to N virtual CPU devices (same escape
        # hatch as __graft_entry__.dryrun_multichip)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count="
            f"{args.dataParallel}").strip()
    import jax

    from bigdl_tpu.parallel.engine import Engine
    if args.dataParallel:
        if len(jax.devices()) < args.dataParallel:
            import jax.extend.backend
            jax.config.update("jax_platforms", "cpu")
            jax.extend.backend.clear_backends()
        Engine.init(axes={"data": args.dataParallel},
                    devices=jax.devices()[:args.dataParallel])
    print(json.dumps(allreduce_bench(args.sizeMB, args.dtype, args.iters)))


if __name__ == "__main__":
    main()

"""Engine — runtime topology initialization.

Reference parity: utils/Engine.scala:206-360. The reference's Engine wires
JVM thread pools (``Engine.default``/``Engine.model``), reads
``DL_NODE_NUMBER``/``DL_CORE_NUMBER`` env vars, pins MKL threads and returns
a SparkConf. On TPU the entire threading runtime disappears (XLA owns op
parallelism); ``Engine.init`` instead builds the **device mesh** that every
distributed component shards over — the TPU equivalent of node/core topology:

- ``data`` axis  — data parallelism (the reference's node-level sync SGD)
- ``model`` axis — tensor parallelism (not in the reference; axis kept open
  so the mesh design scales beyond it, SURVEY §2.6 scoping note)
- ``seq`` axis   — sequence/context parallelism for long-context models

Multi-host: one process per host, all devices enumerated by
``jax.devices()`` — collectives ride ICI within a slice and DCN across
slices, laid out by XLA from the sharding annotations.
"""
from __future__ import annotations

import logging

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger("bigdl_tpu.parallel")

__all__ = ["Engine", "get_mesh", "data_sharding", "replicated"]

_mesh: Mesh | None = None


class Engine:
    """(reference utils/Engine.scala — singleton topology holder)"""

    @staticmethod
    def init(node_number: int | None = None, core_number: int | None = None,
             on_spark: bool = False, *, axes: dict | None = None,
             devices=None) -> Mesh:
        """Build and install the global device mesh.

        ``node_number``/``core_number`` are accepted for reference-API
        parity (Engine.init(node, cores, onSpark), Engine.scala:337-348) —
        their product must match the device count when given. ``axes`` maps
        axis names to sizes, e.g. ``{"data": 4, "model": 2}``; default is
        pure data parallelism over every visible device.
        """
        global _mesh
        import os
        devs = list(devices if devices is not None else jax.devices())
        n = len(devs)
        # env-var surface (reference Engine.scala:232-287:
        # DL_NODE_NUMBER / DL_CORE_NUMBER / DL_ENGINE_TYPE): accepted for
        # script parity; on TPU JAX owns the real topology, so they only
        # feed the same parity warning the explicit args do.
        # DL_ENGINE_TYPE values other than the reference's mklblas are an
        # error there (Engine.scala:272-277) — warn here.
        if node_number is None and os.environ.get("DL_NODE_NUMBER"):
            node_number = int(os.environ["DL_NODE_NUMBER"])
        if core_number is None and os.environ.get("DL_CORE_NUMBER"):
            core_number = int(os.environ["DL_CORE_NUMBER"])
        engine_type = os.environ.get("DL_ENGINE_TYPE")
        if engine_type and engine_type.lower() != "mklblas":
            logger.warning(f"DL_ENGINE_TYPE={engine_type} has no TPU "
                           "equivalent (XLA owns op dispatch); ignored")
        if axes is None:
            if node_number is not None:
                want = node_number * (core_number or 1)
                if want != n:
                    logger.warning(
                        f"Engine.init: node*core = {want} but "
                        f"{n} devices visible; using {n}")
            axes = {"data": n}
        sizes = list(axes.values())
        assert int(np.prod(sizes)) == n, \
            f"mesh axes {axes} do not cover {n} devices"
        mesh_devs = np.asarray(devs).reshape(sizes)
        _mesh = Mesh(mesh_devs, tuple(axes.keys()))
        logger.info(f"Engine initialized: mesh {dict(axes)} over {n} "
                    f"{devs[0].platform} device(s)")
        return _mesh

    @staticmethod
    def node_number() -> int:
        """Data-parallel degree (reference Engine.nodeNumber)."""
        m = get_mesh()
        return int(m.shape.get("data", 1))

    @staticmethod
    def core_number() -> int:
        """Reference Engine.coreNumber — on TPU each shard is one chip."""
        return 1

    @staticmethod
    def is_initialized() -> bool:
        return _mesh is not None

    @staticmethod
    def reset() -> None:
        global _mesh
        _mesh = None


def get_mesh() -> Mesh:
    if _mesh is None:
        Engine.init()
    return _mesh


def data_sharding(mesh: Mesh | None = None, *, axis: str = "data"
                  ) -> NamedSharding:
    """Batch-axis sharding over the data-parallel mesh axis."""
    mesh = mesh or get_mesh()
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh | None = None) -> NamedSharding:
    mesh = mesh or get_mesh()
    return NamedSharding(mesh, P())

"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference handles sequences functionally (scan-unrolled RNNs, padded
batching — SURVEY §5.7) and has no sequence parallelism; on TPU, long
contexts are first-class, so this module provides the two standard schemes
over the mesh's ``seq`` axis (parallel/engine.py reserves it):

- ``ring_attention``: q/k/v stay sequence-sharded; K/V blocks rotate
  around the ring via ``ppermute`` while each shard folds them into a
  numerically-stable online softmax (the Blockwise/RingAttention
  construction — see PAPERS.md "Ring Attention with Blockwise
  Transformers"). Peak memory per chip is O(seq/N), communication rides
  ICI neighbor links, and the result is bit-equivalent to full attention
  up to float summation order.
- ``ulysses_attention``: two ``all_to_all``s re-shard sequence->heads,
  run full local attention per head group, and shard back (the
  DeepSpeed-Ulysses construction). Cheaper collectives for models with
  enough heads; requires heads % mesh[seq] == 0.

Both are pure functions differentiable end-to-end (the ring loop is a
Python unroll over the static mesh size, so autodiff just works), usable
eagerly or inside jit/pjit.

Shapes follow (batch, seq, heads, head_dim) throughout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.parallel.collective import shard_map
from bigdl_tpu.parallel.engine import get_mesh

__all__ = ["dot_product_attention", "ring_attention", "ulysses_attention"]

_NEG = -1e9  # finite mask value: keeps exp(s - m) well-defined everywhere


def _qkv_spec(mesh, axis, batch_axis):
    """Partition spec for (B, S, H, D): sequence on ``axis``, batch on
    ``batch_axis`` ("auto" = the mesh's data axis when present, so a
    dp x sp mesh keeps its batch shards instead of all-gathering them)."""
    if batch_axis == "auto":
        batch_axis = ("data" if "data" in mesh.axis_names
                      and axis != "data" else None)
    return P(batch_axis, axis)


def dot_product_attention(q, k, v, *, causal: bool = False,
                          scale: float | None = None,
                          q_offset: int = 0, kv_offset: int = 0,
                          flash: str | bool = "auto"):
    """Attention over (B, S, H, D).

    ``q_offset``/``kv_offset`` are the global positions of element 0 —
    how causal masking stays correct on sequence shards.

    ``flash="auto"`` routes to the fused Pallas kernel
    (ops/pallas/flash_attention.py) on TPU whenever shapes allow —
    O(S·D) memory instead of the (B,H,S,S) score matrix, measured 2.3x
    faster at S=4096 on v5e and the only path that fits S>=8192.
    The XLA fallback below is the reference semantics (and the CPU/test
    path); both share bf16-operand matmul rounding, so they agree to
    ~1e-3 under a temperate softmax.
    """
    if flash:
        from bigdl_tpu.ops.pallas.flash_attention import (flash_attention,
                                                          flash_supported)
        offsets_ok = not causal or (q_offset == 0 and kv_offset == 0)
        supported = offsets_ok and flash_supported(q, k)
        if flash is True and not supported:
            raise ValueError(
                f"flash=True but the kernel does not support this call: "
                f"backend={jax.default_backend()}, q{q.shape} k{k.shape}, "
                f"q_offset={q_offset} kv_offset={kv_offset} (need TPU, "
                f"seq % 128 == 0, head_dim % 64 == 0, zero offsets when "
                f"causal)")
        if supported:
            return flash_attention(q, k, v, causal=causal, scale=scale)
    f32 = jnp.float32
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(f32), k.astype(f32)) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])[:, None]
        kpos = kv_offset + jnp.arange(k.shape[1])[None, :]
        s = jnp.where((kpos > qpos)[None, None], _NEG, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(f32)).astype(q.dtype)


def _merge_blocks(o, lse, o_t, lse_t):
    """Fold a block's (o_t, lse_t) into the running (o, lse) — the
    standard blockwise-softmax merge (numerically safe when either side
    is -inf, i.e. empty)."""
    m = jnp.maximum(lse, lse_t)
    # guard fully-empty rows (both -inf): keep weights at 0
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    w, w_t = jnp.exp(lse - m_safe), jnp.exp(lse_t - m_safe)
    denom = w + w_t
    d_safe = jnp.where(denom == 0.0, 1.0, denom)
    o_new = (o * w[..., None] + o_t * w_t[..., None]) / d_safe[..., None]
    return o_new, m_safe + jnp.log(d_safe)


def _ring_body_flash(q, k, v, *, axis, n, causal, scale, interpret,
                     kv_groups=1):
    """Ring attention whose per-step local attention is the fused Pallas
    flash kernel: each rotating K/V block contributes (o_t, lse_t) and the
    shards merge by logsumexp. Per-chip live memory is O(S_local * D) —
    the (S_local, S_local) score tile never exists outside VMEM.

    Causal masking needs no traced offsets inside the kernel: a block is
    fully-visible (source shard before mine), diagonal (same shard —
    plain local causal mask), or fully-masked (skipped via lax.switch).
    """
    from bigdl_tpu.ops.pallas.flash_attention import flash_attention_with_lse
    f32 = jnp.float32
    b, sq, h, d = q.shape
    idx = jax.lax.axis_index(axis)
    o = jnp.zeros((b, sq, h, d), f32)
    lse = jnp.full((b, sq, h), -jnp.inf, f32)
    perm = [(j, (j - 1) % n) for j in range(n)]  # receive from the right

    def full_fn(q, k, v):
        o_t, l_t = flash_attention_with_lse(q, k, v, causal=False,
                                            scale=scale, interpret=interpret)
        return o_t.astype(f32), l_t

    def diag_fn(q, k, v):
        o_t, l_t = flash_attention_with_lse(q, k, v, causal=True,
                                            scale=scale, interpret=interpret)
        return o_t.astype(f32), l_t

    def skip_fn(q, k, v):
        return jnp.zeros((b, sq, h, d), f32), jnp.full((b, sq, h), -jnp.inf,
                                                       f32)

    for t in range(n):
        src = (idx + t) % n                      # global block id of k/v
        # GQA: narrow (kv-head) blocks ride the ring; widen to the query
        # head count only for the local attention math (review finding:
        # a pre-ring repeat multiplied ring bytes by the group factor)
        ke = jnp.repeat(k, kv_groups, axis=2) if kv_groups > 1 else k
        ve = jnp.repeat(v, kv_groups, axis=2) if kv_groups > 1 else v
        if causal:
            case = jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2))
            o_t, lse_t = jax.lax.switch(case, (full_fn, diag_fn, skip_fn),
                                        q, ke, ve)
        else:
            o_t, lse_t = full_fn(q, ke, ve)
        o, lse = _merge_blocks(o, lse, o_t, lse_t)
        if t != n - 1:
            k = jax.lax.ppermute(k, axis, perm)
            v = jax.lax.ppermute(v, axis, perm)
    return o.astype(q.dtype)


def _ring_body(q, k, v, *, axis, n, causal, scale, kv_groups=1):
    """Per-shard ring attention: local q block, rotating k/v blocks."""
    f32 = jnp.float32
    b, sq, h, d = q.shape
    skv = k.shape[1]
    idx = jax.lax.axis_index(axis)
    qf = q.astype(f32) * scale

    m = jnp.full((b, h, sq), -jnp.inf, f32)     # running row max
    l = jnp.zeros((b, h, sq), f32)              # running denominator
    o = jnp.zeros((b, sq, h, d), f32)           # running numerator
    perm = [(j, (j - 1) % n) for j in range(n)]  # receive from the right

    for t in range(n):
        src = (idx + t) % n                      # global block id of k/v
        ke = jnp.repeat(k, kv_groups, axis=2) if kv_groups > 1 else k
        v_use = jnp.repeat(v, kv_groups, axis=2) if kv_groups > 1 else v
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, ke.astype(f32))
        if causal:
            qpos = idx * sq + jnp.arange(sq)[:, None]
            kpos = src * skv + jnp.arange(skv)[None, :]
            s = jnp.where((kpos > qpos)[None, None], _NEG, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # exp(-inf - -inf) can't arise: s is finite (mask is finite)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * jnp.moveaxis(corr, 1, 2)[..., None] \
            + jnp.einsum("bhqk,bkhd->bqhd", p, v_use.astype(f32))
        m = m_new
        if t != n - 1:
            k = jax.lax.ppermute(k, axis, perm)
            v = jax.lax.ppermute(v, axis, perm)

    out = o / jnp.moveaxis(l, 1, 2)[..., None]
    return out.astype(q.dtype)


def _flash_ring_ok(q, k, q_local, kv_local, causal, flash,
                   interpret=False):
    """Whether the per-shard flash path applies (mirrors flash_supported,
    but on the LOCAL shard lengths). ``flash=True`` raises when the
    kernel cannot serve the call — same contract as
    ``dot_product_attention``; "auto" quietly falls back.

    Causal additionally requires equal q/kv shard lengths: the ring
    block classification (src < idx fully visible, src == idx local
    causal) only matches global-position masking when the shards are the
    same length (_ring_body masks on idx*sq vs src*skv and stays correct
    for cross-length causal calls).
    """
    if flash is False:
        return False
    from bigdl_tpu.ops.pallas.flash_attention import _Q_BLOCKS
    shapes_ok = (q_local % _Q_BLOCKS[-1] == 0
                 and kv_local % _Q_BLOCKS[-1] == 0
                 and k.shape[-1] % 64 == 0
                 and not (causal and q_local != kv_local))
    if flash is True and not shapes_ok:
        raise ValueError(
            f"flash=True but the ring flash path does not support this "
            f"call: local shards q={q_local} kv={kv_local}, "
            f"head_dim={k.shape[-1]}, causal={causal} (need shard "
            f"lengths % 128 == 0, head_dim % 64 == 0, and equal q/kv "
            f"shard lengths when causal)")
    if flash is True and not interpret and jax.default_backend() != "tpu":
        # advisor r2: without this the compiled Pallas lowering fails
        # deep inside Mosaic with an obscure error on CPU/GPU
        raise ValueError(
            "flash=True requires the TPU backend (or interpret=True for "
            "CPU testing); this process is running on "
            f"'{jax.default_backend()}'")
    if flash == "auto":
        return shapes_ok and jax.default_backend() == "tpu"
    return shapes_ok


def ring_attention(q, k, v, *, causal: bool = False,
                   scale: float | None = None, axis: str = "seq",
                   mesh: Mesh | None = None, batch_axis="auto",
                   flash: str | bool = "auto", interpret: bool = False,
                   kv_groups: int = 1):
    """Sequence-parallel attention; q/k/v sharded on dim 1 over ``axis``.

    Call eagerly with global arrays (this wrapper shards them) or use
    ``ring_attention_sharded`` inside an existing shard_map/pjit region.

    ``flash="auto"`` runs each shard's local block attention through the
    fused Pallas kernel on TPU when the local shard length divides the
    kernel tiles (O(S_local*D) live memory); ``flash=False`` keeps the
    XLA online-softmax body; ``flash=True`` forces the kernel
    (``interpret=True`` for CPU testing).
    """
    mesh = mesh or get_mesh()
    n = mesh.shape[axis]
    if q.shape[1] % n or k.shape[1] % n:
        raise ValueError(
            f"sequence length {q.shape[1]}/{k.shape[1]} not divisible by "
            f"mesh axis '{axis}' size {n}")
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    use_flash = _flash_ring_ok(q, k, q.shape[1] // n, k.shape[1] // n,
                               causal, flash, interpret)

    def body(qb, kb, vb):
        if use_flash:
            return _ring_body_flash(qb, kb, vb, axis=axis, n=n,
                                    causal=causal, scale=scale,
                                    interpret=interpret,
                                    kv_groups=kv_groups)
        return _ring_body(qb, kb, vb, axis=axis, n=n, causal=causal,
                          scale=scale, kv_groups=kv_groups)

    spec = _qkv_spec(mesh, axis, batch_axis)
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)(q, k, v)


def ring_attention_sharded(q, k, v, *, causal: bool = False,
                           scale: float | None = None, axis: str = "seq",
                           axis_size: int | None = None,
                           flash: str | bool = "auto",
                           interpret: bool = False, kv_groups: int = 1):
    """The per-shard ring computation, for use INSIDE shard_map/pjit where
    ``q``/``k``/``v`` are already the local sequence blocks."""
    n = axis_size if axis_size is not None else jax.lax.axis_size(axis)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if _flash_ring_ok(q, k, q.shape[1], k.shape[1], causal, flash,
                      interpret):
        return _ring_body_flash(q, k, v, axis=axis, n=n, causal=causal,
                                scale=scale, interpret=interpret,
                                kv_groups=kv_groups)
    return _ring_body(q, k, v, axis=axis, n=n, causal=causal, scale=scale,
                      kv_groups=kv_groups)


def ulysses_attention(q, k, v, *, causal: bool = False,
                      scale: float | None = None, axis: str = "seq",
                      mesh: Mesh | None = None, batch_axis="auto",
                      kv_groups: int = 1):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses scheme).

    Re-shards (B, S/N, H, D) -> (B, S, H/N, D) with one all_to_all, runs
    exact local attention over the full sequence for its head group, and
    re-shards back. Requires H % N == 0.

    ``kv_groups`` > 1 (GQA): pass k/v at their NARROW kv-head width —
    they cross the all_to_all at kv width (kv_groups-times less wire
    traffic than pre-widened) and widen locally after the re-shard.
    Alignment holds because head chunks are contiguous: widened
    chunk-local head t maps to chunk-local kv head t // kv_groups,
    which is the global h // kv_groups grouping restricted to the
    chunk. Falls back to pre-widening when the kv heads don't divide
    the axis (e.g. MQA on a mesh wider than the kv-head count).
    """
    mesh = mesh or get_mesh()
    n = mesh.shape[axis]
    if q.shape[2] % n:
        raise ValueError(f"heads {q.shape[2]} not divisible by mesh axis "
                         f"'{axis}' size {n}")
    if q.shape[1] % n or k.shape[1] % n:
        raise ValueError(
            f"sequence length {q.shape[1]}/{k.shape[1]} not divisible by "
            f"mesh axis '{axis}' size {n}")
    if kv_groups > 1:
        if kv_groups * k.shape[2] != q.shape[2]:
            raise ValueError(
                f"kv_groups={kv_groups} x {k.shape[2]} kv heads != "
                f"{q.shape[2]} query heads — pass k/v at their narrow "
                "kv-head width (or kv_groups=1 for pre-widened)")
        if k.shape[2] % n:
            k = jnp.repeat(k, kv_groups, axis=2)
            v = jnp.repeat(v, kv_groups, axis=2)
            kv_groups = 1

    def body(qb, kb, vb):
        # seq-sharded -> head-sharded: split heads, gather sequence
        def to_heads(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)
        qh, kh, vh = to_heads(qb), to_heads(kb), to_heads(vb)
        if kv_groups > 1:      # widen AFTER the wire (GQA)
            kh = jnp.repeat(kh, kv_groups, axis=2)
            vh = jnp.repeat(vh, kv_groups, axis=2)
        out = dot_product_attention(qh, kh, vh, causal=causal, scale=scale)
        # head-sharded -> seq-sharded
        return jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    spec = _qkv_spec(mesh, axis, batch_axis)
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)(q, k, v)

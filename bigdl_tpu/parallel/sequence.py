"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference handles sequences functionally (scan-unrolled RNNs, padded
batching — SURVEY §5.7) and has no sequence parallelism; on TPU, long
contexts are first-class, so this module provides the two standard schemes
over the mesh's ``seq`` axis (parallel/engine.py reserves it):

- ``ring_attention``: q/k/v stay sequence-sharded; K/V blocks rotate
  around the ring via ``ppermute`` while each shard folds them into a
  numerically-stable online softmax (the Blockwise/RingAttention
  construction — see PAPERS.md "Ring Attention with Blockwise
  Transformers"). Peak memory per chip is O(seq/N), communication rides
  ICI neighbor links, and the result is bit-equivalent to full attention
  up to float summation order.
- ``ulysses_attention``: two ``all_to_all``s re-shard sequence->heads,
  run full local attention per head group, and shard back (the
  DeepSpeed-Ulysses construction). Cheaper collectives for models with
  enough heads; requires heads % mesh[seq] == 0.

Both are pure functions differentiable end-to-end (the ring loop is a
Python unroll over the static mesh size, so autodiff just works), usable
eagerly or inside jit/pjit.

Shapes follow (batch, seq, heads, head_dim) throughout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.parallel.collective import shard_map
from bigdl_tpu.parallel.engine import get_mesh

__all__ = ["dot_product_attention", "ring_attention", "ulysses_attention"]

_NEG = -1e9  # finite mask value: keeps exp(s - m) well-defined everywhere


def _qkv_spec(mesh, axis, batch_axis):
    """Partition spec for (B, S, H, D): sequence on ``axis``, batch on
    ``batch_axis`` ("auto" = the mesh's data axis when present, so a
    dp x sp mesh keeps its batch shards instead of all-gathering them)."""
    if batch_axis == "auto":
        batch_axis = ("data" if "data" in mesh.axis_names
                      and axis != "data" else None)
    return P(batch_axis, axis)


def dot_product_attention(q, k, v, *, causal: bool = False,
                          scale: float | None = None,
                          q_offset: int = 0, kv_offset: int = 0,
                          flash: str | bool = "auto"):
    """Attention over (B, S, H, D).

    ``q_offset``/``kv_offset`` are the global positions of element 0 —
    how causal masking stays correct on sequence shards.

    ``flash="auto"`` routes to the fused Pallas kernel
    (ops/pallas/flash_attention.py) on TPU whenever shapes allow —
    O(S·D) memory instead of the (B,H,S,S) score matrix, measured 2.3x
    faster at S=4096 on v5e and the only path that fits S>=8192.
    The XLA fallback below is the reference semantics (and the CPU/test
    path); both share bf16-operand matmul rounding, so they agree to
    ~1e-3 under a temperate softmax.
    """
    if flash:
        from bigdl_tpu.ops.pallas.flash_attention import (flash_attention,
                                                          flash_supported)
        offsets_ok = not causal or (q_offset == 0 and kv_offset == 0)
        supported = offsets_ok and flash_supported(q, k)
        if flash is True and not supported:
            raise ValueError(
                f"flash=True but the kernel does not support this call: "
                f"backend={jax.default_backend()}, q{q.shape} k{k.shape}, "
                f"q_offset={q_offset} kv_offset={kv_offset} (need TPU, "
                f"seq % 128 == 0, head_dim % 128 == 0, zero offsets when "
                f"causal)")
        if supported:
            return flash_attention(q, k, v, causal=causal, scale=scale)
    f32 = jnp.float32
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(f32), k.astype(f32)) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])[:, None]
        kpos = kv_offset + jnp.arange(k.shape[1])[None, :]
        s = jnp.where((kpos > qpos)[None, None], _NEG, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(f32)).astype(q.dtype)


def _ring_body(q, k, v, *, axis, n, causal, scale):
    """Per-shard ring attention: local q block, rotating k/v blocks."""
    f32 = jnp.float32
    b, sq, h, d = q.shape
    skv = k.shape[1]
    idx = jax.lax.axis_index(axis)
    qf = q.astype(f32) * scale

    m = jnp.full((b, h, sq), -jnp.inf, f32)     # running row max
    l = jnp.zeros((b, h, sq), f32)              # running denominator
    o = jnp.zeros((b, sq, h, d), f32)           # running numerator
    perm = [(j, (j - 1) % n) for j in range(n)]  # receive from the right

    for t in range(n):
        src = (idx + t) % n                      # global block id of k/v
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k.astype(f32))
        if causal:
            qpos = idx * sq + jnp.arange(sq)[:, None]
            kpos = src * skv + jnp.arange(skv)[None, :]
            s = jnp.where((kpos > qpos)[None, None], _NEG, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # exp(-inf - -inf) can't arise: s is finite (mask is finite)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * jnp.moveaxis(corr, 1, 2)[..., None] \
            + jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(f32))
        m = m_new
        if t != n - 1:
            k = jax.lax.ppermute(k, axis, perm)
            v = jax.lax.ppermute(v, axis, perm)

    out = o / jnp.moveaxis(l, 1, 2)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, *, causal: bool = False,
                   scale: float | None = None, axis: str = "seq",
                   mesh: Mesh | None = None, batch_axis="auto"):
    """Sequence-parallel attention; q/k/v sharded on dim 1 over ``axis``.

    Call eagerly with global arrays (this wrapper shards them) or use
    ``ring_attention_sharded`` inside an existing shard_map/pjit region.
    """
    mesh = mesh or get_mesh()
    n = mesh.shape[axis]
    if q.shape[1] % n or k.shape[1] % n:
        raise ValueError(
            f"sequence length {q.shape[1]}/{k.shape[1]} not divisible by "
            f"mesh axis '{axis}' size {n}")
    scale = scale if scale is not None else q.shape[-1] ** -0.5

    def body(qb, kb, vb):
        return _ring_body(qb, kb, vb, axis=axis, n=n, causal=causal,
                          scale=scale)

    spec = _qkv_spec(mesh, axis, batch_axis)
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)(q, k, v)


def ring_attention_sharded(q, k, v, *, causal: bool = False,
                           scale: float | None = None, axis: str = "seq",
                           axis_size: int | None = None):
    """The per-shard ring computation, for use INSIDE shard_map/pjit where
    ``q``/``k``/``v`` are already the local sequence blocks."""
    n = axis_size if axis_size is not None else jax.lax.axis_size(axis)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _ring_body(q, k, v, axis=axis, n=n, causal=causal, scale=scale)


def ulysses_attention(q, k, v, *, causal: bool = False,
                      scale: float | None = None, axis: str = "seq",
                      mesh: Mesh | None = None, batch_axis="auto"):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses scheme).

    Re-shards (B, S/N, H, D) -> (B, S, H/N, D) with one all_to_all, runs
    exact local attention over the full sequence for its head group, and
    re-shards back. Requires H % N == 0.
    """
    mesh = mesh or get_mesh()
    n = mesh.shape[axis]
    if q.shape[2] % n:
        raise ValueError(f"heads {q.shape[2]} not divisible by mesh axis "
                         f"'{axis}' size {n}")
    if q.shape[1] % n or k.shape[1] % n:
        raise ValueError(
            f"sequence length {q.shape[1]}/{k.shape[1]} not divisible by "
            f"mesh axis '{axis}' size {n}")

    def body(qb, kb, vb):
        # seq-sharded -> head-sharded: split heads, gather sequence
        def to_heads(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)
        qh, kh, vh = to_heads(qb), to_heads(kb), to_heads(vb)
        out = dot_product_attention(qh, kh, vh, causal=causal, scale=scale)
        # head-sharded -> seq-sharded
        return jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    spec = _qkv_spec(mesh, axis, batch_axis)
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)(q, k, v)

"""Pipeline parallelism: SPMD GPipe over a mesh axis.

The reference has no pipeline parallelism (whole model per executor). The
TPU-native construction (the scaling-book recipe): L IDENTICAL layers are
stacked parameter-wise, the stack is sharded over the ``model`` axis so
each device owns L/S consecutive layers, and microbatches stream through
the stages with activations hopping stage-to-stage via ``ppermute``
(neighbor ICI links). All devices run the same program — stage identity
comes from ``lax.axis_index`` — so the whole thing jits as one SPMD
computation and autodiff produces the reverse pipeline automatically.

Homogeneity is the honest constraint: heterogeneous ``Sequential`` stages
cannot ride one SPMD program. That matches where pipelining earns its keep
(deep stacks of identical blocks).

Schedule: GPipe-style fill-drain over T = M + S - 1 ticks for M
microbatches and S stages; bubble fraction (S-1)/T shrinks as M grows.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.parallel.collective import shard_map
from bigdl_tpu.parallel.engine import get_mesh

__all__ = ["pipeline_apply", "stack_layer_params",
           "pipeline_schedule_stats"]


def stack_layer_params(params_list):
    """Stack per-layer param pytrees into one tree with a leading layer
    axis (what ``pipeline_apply`` consumes and what gets sharded)."""
    return jax.tree.map(lambda *ls: jnp.stack(ls), *params_list)


def pipeline_schedule_stats(num_microbatches: int, n_stages: int) -> dict:
    """Fill-drain cost of the GPipe schedule, as numbers instead of a
    docstring claim: T = M + S - 1 ticks move M microbatches through S
    stages, of which S - 1 are bubble (each stage idles while the
    pipeline fills and drains), so ``bubble_fraction`` =
    (S-1)/(M+S-1) of every device's tick budget is fill-drain cost.
    ``pipeline_apply(..., with_stats=True)`` returns this dict next to
    the result so runs REPORT the cost they pay."""
    m, s = int(num_microbatches), int(n_stages)
    if m < 1 or s < 1:
        raise ValueError(f"need microbatches >= 1 and stages >= 1, got "
                         f"M={m}, S={s}")
    ticks = m + s - 1
    return {"microbatches": m, "stages": s, "ticks": ticks,
            "bubble_ticks": s - 1,
            "bubble_fraction": (s - 1) / ticks}


def _local_stack_apply(layer_apply, local_params, x):
    """Run this stage's L/S stacked layers in sequence via lax.scan."""

    def body(h, layer_p):
        return layer_apply(layer_p, h), None

    y, _ = jax.lax.scan(body, x, local_params)
    return y


def pipeline_apply(layer_apply, stacked_params, x, *,
                   num_microbatches: int, axis: str = "model",
                   mesh: Mesh | None = None, data_axis: str | None = None,
                   with_stats: bool = False):
    """Apply L stacked identical layers to ``x`` through an S-stage
    pipeline over mesh ``axis``.

    ``layer_apply(layer_params, h) -> h`` is one layer's pure function;
    ``stacked_params`` leaves have leading dim L (see
    ``stack_layer_params``); L must divide by the axis size S, the batch
    by ``num_microbatches``. Differentiable end-to-end; returns the same
    result as serially applying the L layers (up to float order).

    ``data_axis`` composes the pipeline with data parallelism: the batch
    dim shards over that mesh axis and each data-parallel row of the mesh
    runs its own fill-drain pipeline over its batch shard (params stay
    pipeline-sharded, replicated across ``data_axis``).
    ``num_microbatches`` must then divide the per-row batch shard.

    ``with_stats=True`` returns ``(y, stats)`` where ``stats`` is
    :func:`pipeline_schedule_stats` for this run's (M, S) — the
    schedule's fill-drain bubble fraction (S-1)/(M+S-1) reported
    instead of hidden (tests/test_pipeline_parallel.py pins it).
    """
    mesh = mesh or get_mesh()
    s = mesh.shape[axis]
    n_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_layers % s:
        raise ValueError(f"{n_layers} layers not divisible by "
                         f"{s} pipeline stages")
    batch = x.shape[0]
    if data_axis is not None:
        d = mesh.shape[data_axis]
        if batch % d:
            raise ValueError(f"batch {batch} not divisible by "
                             f"data axis {d}")
        batch = batch // d           # per-row shard seen inside the body
    if batch % num_microbatches:
        raise ValueError(f"batch {batch} not divisible by "
                         f"{num_microbatches} microbatches")
    mb = batch // num_microbatches
    m = num_microbatches

    def body(local_params, xb):
        # local_params leaves: (L/S, ...) — this stage's layer block
        stage = jax.lax.axis_index(axis)
        mbs = xb.reshape((m, mb) + xb.shape[1:])
        perm = [(i, (i + 1) % s) for i in range(s)]  # downstream hop

        def tick(state, t):
            # lax.scan keeps the program size constant in M and S —
            # a Python unroll doubled the jaxpr per extra microbatch
            carry, out = state
            # stage 0 injects microbatch t; others take the upstream hop
            feed = jnp.take(mbs, jnp.minimum(t, m - 1), axis=0)
            h = jnp.where(stage == 0, feed, carry)
            y = _local_stack_apply(layer_apply, local_params, h)
            # the LAST stage finished microbatch t-(s-1) this tick
            oi = t - (s - 1)
            valid = (stage == (s - 1)) & (oi >= 0)
            slot = jnp.clip(oi, 0, m - 1)
            out = out.at[slot].set(
                jnp.where(valid, y, jnp.take(out, slot, axis=0)))
            carry = jax.lax.ppermute(y, axis, perm)
            return (carry, out), None

        init = (jnp.zeros_like(mbs[0]), jnp.zeros_like(mbs))
        (_, out), _ = jax.lax.scan(tick, init,
                                   jnp.arange(m + s - 1))
        # outputs are populated only on the last stage; psum replicates
        # them (zeros elsewhere keep the sum exact)
        out = jax.lax.psum(out, axis)
        return out.reshape((batch,) + out.shape[2:])

    pspec = jax.tree.map(lambda _: P(axis), stacked_params)
    xspec = P() if data_axis is None else P(data_axis)
    y = shard_map(
        body, mesh=mesh,
        in_specs=(pspec, xspec),
        out_specs=xspec,
        check_rep=False)(stacked_params, x)
    if with_stats:
        return y, pipeline_schedule_stats(m, s)
    return y

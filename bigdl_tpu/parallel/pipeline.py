"""Pipeline parallelism: SPMD pipeline schedules over a mesh axis.

The reference has no pipeline parallelism (whole model per executor). The
TPU-native construction (the scaling-book recipe): L IDENTICAL layers are
stacked parameter-wise, the stack is sharded over a mesh axis so each
device owns L/S consecutive layers, and microbatches stream through the
stages with activations hopping stage-to-stage via ``ppermute`` (neighbor
ICI links). All devices run the same program — stage identity comes from
``lax.axis_index`` — so the whole thing jits as one SPMD computation.

Homogeneity is the honest constraint: heterogeneous ``Sequential`` stages
cannot ride one SPMD program. That matches where pipelining earns its keep
(deep stacks of identical blocks).

Two layers of machinery live here:

- :func:`pipeline_apply` — the original forward-only GPipe fill-drain
  apply (autodiff produces the reverse pipeline), kept for inference-style
  uses and as the simplest construction.
- The **schedule machinery** (ISSUE 11): explicit unit-level schedules
  (``gpipe`` / ``1f1b`` / ``interleaved_1f1b``) generated as per-device
  ordered (forward | backward, chunk, microbatch) unit lists, an exact
  event simulation that derives each schedule's bubble fraction and
  activation-stash bound, a *measured* bubble fraction that feeds real
  per-stage span timings through the same dependency graph
  (:func:`measure_pipeline_bubble`), and :class:`PipelineParallel` — the
  production train-step construction ``DistriOptimizer`` drives
  (``pipeline_stages=`` / ``set_pipeline()``): one compiled step that
  scans the combined forward/backward schedule with manual per-chunk
  ``jax.vjp``, a bounded activation stash, gradients accumulated in
  donated scan carries, and the optimizer update firing exactly once per
  accumulated step — the same microbatching contract as
  ``set_grad_accumulation(k)`` (optim/accumulation.py).

Schedule cost model (docs/PERFORMANCE.md has the table):

- ``gpipe``       — all forwards then all backwards; bubble fraction
                    (S-1)/(M+S-1); every one of the M microbatches'
                    activations is live at the turnaround (stash M).
- ``1f1b``        — steady-state one-forward-one-backward; the SAME
                    bubble fraction (S-1)/(M+S-1) — the schedule's win is
                    the activation stash, bounded by ~S in-flight
                    microbatches instead of M, independent of M.
- ``interleaved_1f1b`` — each device owns ``v`` non-contiguous chunks of
                    L/(S*v) layers (round-robin placement); fill/drain
                    shrinks by v: bubble fraction (S-1)/(v*M+S-1) —
                    STRICTLY below GPipe's for v > 1 at the same (S, M),
                    at the cost of v-1 extra inter-stage hops per
                    microbatch. This is the schedule the bench row's
                    measured receipt compares against GPipe.
"""
from __future__ import annotations

import logging
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.parallel.collective import shard_map
from bigdl_tpu.parallel.engine import get_mesh

logger = logging.getLogger("bigdl_tpu.parallel")

__all__ = ["pipeline_apply", "stack_layer_params",
           "pipeline_schedule_stats", "PIPELINE_SCHEDULES",
           "check_pipeline_schedule", "pipeline_schedule_order",
           "PipelineSchedule", "simulate_schedule",
           "measure_pipeline_bubble", "partition_sequential",
           "PipelineParallel"]

PIPELINE_SCHEDULES = ("gpipe", "1f1b", "interleaved_1f1b")


def check_pipeline_schedule(name: str) -> str:
    name = "1f1b" if name is None else str(name)
    if name not in PIPELINE_SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {name!r} "
                         f"(known: {list(PIPELINE_SCHEDULES)})")
    return name


def stack_layer_params(params_list):
    """Stack per-layer param pytrees into one tree with a leading layer
    axis (what ``pipeline_apply`` consumes and what gets sharded)."""
    return jax.tree.map(lambda *ls: jnp.stack(ls), *params_list)


# ---------------------------------------------------------------------------
# schedule generation: per-device ordered (kind, chunk, microbatch) units
# ---------------------------------------------------------------------------

@dataclass
class PipelineSchedule:
    """One generated schedule: per-device unit orders plus the exact
    unit-tick timeline properties derived from them. ``orders[d]`` is
    device ``d``'s execution order of ``("F"|"B", global_chunk, mb)``
    units; ``starts`` maps each unit to its unit-tick start. Windows are
    the exact buffer bounds the SPMD executor sizes its stash with."""
    num_microbatches: int
    n_stages: int
    schedule: str
    virtual_stages: int
    orders: list = field(repr=False)
    starts: dict = field(repr=False)
    makespan: int = 0
    bubble_fraction: float = 0.0
    peak_stash_microbatches: int = 0
    act_window: int = 1
    cot_window: int = 1

    @property
    def n_chunks(self) -> int:
        return self.n_stages * self.virtual_stages


def _list_schedule(orders, n_chunks, fwd_s, bwd_s):
    """Earliest-start timing of fixed per-device unit orders under the
    pipeline dependency DAG: ``F(g, m)`` needs ``F(g-1, m)``; ``B(g,
    m)`` needs ``F(g, m)`` and ``B(g+1, m)`` (the cotangent flows
    downstream; the last chunk's backward seeds from the loss). Returns
    (starts, done, makespan, busy_per_device)."""
    done: dict = {}
    starts: dict = {}
    free = [0.0] * len(orders)
    ptr = [0] * len(orders)
    total = sum(len(o) for o in orders)
    placed = 0
    while placed < total:
        progressed = False
        for d, order in enumerate(orders):
            while ptr[d] < len(order):
                kind, g, mb = order[ptr[d]]
                deps = ([("F", g - 1, mb)] if g > 0 else []) \
                    if kind == "F" else \
                    [("F", g, mb)] + ([("B", g + 1, mb)]
                                      if g < n_chunks - 1 else [])
                if any(u not in done for u in deps):
                    break
                start = max([free[d]] + [done[u] for u in deps])
                dur = fwd_s[d] if kind == "F" else bwd_s[d]
                starts[(kind, g, mb)] = start
                done[(kind, g, mb)] = start + dur
                free[d] = start + dur
                ptr[d] += 1
                placed += 1
                progressed = True
        if not progressed:
            raise RuntimeError("pipeline schedule deadlocked — invalid "
                               "unit order")
    busy = [sum(fwd_s[d] if k == "F" else bwd_s[d] for k, _, _ in o)
            for d, o in enumerate(orders)]
    return starts, done, max(free), busy


def pipeline_schedule_order(num_microbatches: int, n_stages: int,
                            schedule: str = "1f1b",
                            virtual_stages: int = 1) -> PipelineSchedule:
    """Generate the unit-level schedule as explicit per-device orders.

    - ``gpipe``: all forwards in microbatch order, then all backwards in
      REVERSE microbatch order (what autodiff of the forward fill-drain
      scan produces). ``virtual_stages`` must be 1.
    - ``1f1b`` (PipeDream-flush): device ``d`` runs ``S-d-1`` warmup
      forwards, then steady one-forward-one-backward pairs, then drains
      backwards — backwards retire in microbatch order 0..M-1, matching
      ``set_grad_accumulation``'s j=0..k-1 gradient-add order, with at
      most ``S-d`` microbatches in flight (independent of M).
    - ``interleaved_1f1b`` (Megatron-style): each device owns ``v``
      round-robin chunks; microbatches advance in groups of S sweeping
      the chunks, warmup is ``2*(S-d-1) + (v-1)*S`` virtual steps, and
      the backward sweep mirrors the forward with chunks reversed.
      Requires M divisible by S.
    """
    m, s = int(num_microbatches), int(n_stages)
    v = int(virtual_stages)
    schedule = check_pipeline_schedule(schedule)
    if m < 1 or s < 1 or v < 1:
        raise ValueError(f"need microbatches/stages/virtual_stages >= 1, "
                         f"got M={m}, S={s}, v={v}")
    if schedule != "interleaved_1f1b" and v != 1:
        raise ValueError(f"virtual_stages={v} only applies to "
                         f"'interleaved_1f1b' (got {schedule!r})")
    c = s * v
    orders = []
    if schedule == "gpipe":
        for d in range(s):
            orders.append([("F", d, j) for j in range(m)]
                          + [("B", d, j) for j in reversed(range(m))])
    elif schedule == "1f1b":
        for d in range(s):
            w = min(m, s - d - 1)
            o = [("F", d, j) for j in range(w)]
            for j in range(m - w):
                o.append(("F", d, w + j))
                o.append(("B", d, j))
            o += [("B", d, j) for j in range(m - w, m)]
            orders.append(o)
    else:
        if m % s:
            raise ValueError(
                f"interleaved_1f1b advances microbatches in groups of "
                f"S: num_microbatches {m} must divide by {s} stages")

        def unit(d, k, forward):
            kg = k % c
            cl = kg // s
            if not forward:
                cl = v - 1 - cl
            mb = (k // c) * s + (kg % s)
            return ("F" if forward else "B", cl * s + d, mb)

        total = v * m
        for d in range(s):
            w = min(total, 2 * (s - d - 1) + (v - 1) * s)
            o = [unit(d, k, True) for k in range(w)]
            for j in range(total - w):
                o.append(unit(d, w + j, True))
                o.append(unit(d, j, False))
            o += [unit(d, j, False) for j in range(total - w, total)]
            orders.append(o)

    starts_f, done_f, makespan_f, _ = _list_schedule(
        orders, c, [1.0] * s, [1.0] * s)
    starts = {u: int(round(t)) for u, t in starts_f.items()}
    done = {u: int(round(t)) for u, t in done_f.items()}
    makespan = int(round(makespan_f))
    bubble = 1.0 - 2 * v * m / makespan

    # exact buffer windows: the activation stash slot of (g, mb) is live
    # from the upstream forward's completion (its own forward start for
    # chunk 0) until its backward completes; the cotangent slot from the
    # downstream backward's completion until its own backward completes.
    def _span(intervals):
        # minimal window W for mb-mod-W slot reuse: any two LIVE-AT-
        # THE-SAME-TIME microbatches must land on distinct slots, so W
        # exceeds the largest index gap among pairwise-overlapping
        # intervals
        worst = 1
        for i, (a_i, e_i) in intervals.items():
            for j, (a_j, e_j) in intervals.items():
                if j > i and a_j < e_i and a_i < e_j:
                    worst = max(worst, j - i + 1)
        return worst

    act_w, cot_w, peak = 1, 1, 1
    for g in range(c):
        acts = {}
        for mb in range(m):
            a = (done[("F", g - 1, mb)] if g > 0
                 else starts[("F", g, mb)])
            acts[mb] = (a, done[("B", g, mb)])
        act_w = max(act_w, _span(acts))
        live = [sum(1 for a, e in acts.values() if a <= tt < e)
                for tt in range(makespan)]
        peak = max(peak, max(live))
        if g < c - 1:
            cots = {mb: (done[("B", g + 1, mb)], done[("B", g, mb)])
                    for mb in range(m)}
            cot_w = max(cot_w, _span(cots))

    return PipelineSchedule(
        num_microbatches=m, n_stages=s, schedule=schedule,
        virtual_stages=v, orders=orders, starts=starts,
        makespan=makespan, bubble_fraction=bubble,
        peak_stash_microbatches=peak, act_window=act_w, cot_window=cot_w)


def pipeline_schedule_stats(num_microbatches: int, n_stages: int,
                            schedule: str = "gpipe", *,
                            virtual_stages: int = 1) -> dict:
    """Schedule cost as numbers instead of a docstring claim.

    ``schedule="gpipe"`` (the default) keeps the original fill-drain
    contract exactly — ``ticks`` = M+S-1 forward ticks, ``bubble_ticks``
    = S-1, ``bubble_fraction`` = (S-1)/(M+S-1) — the fraction is
    identical under combined forward+backward accounting, so the legacy
    fields stay honest. ``"1f1b"`` and ``"interleaved_1f1b"`` report the
    combined schedule: ``ticks`` is the fwd+bwd makespan in unit ticks,
    ``bubble_fraction`` the exact per-device idle share derived from the
    generated schedule (closed forms: (S-1)/(M+S-1) for 1f1b — equal to
    GPipe's, its win is the stash — and (S-1)/(v·M+S-1) for interleaved,
    strictly below GPipe's for v > 1). ``peak_stash_microbatches`` is
    the schedule's exact in-flight activation bound — the memory half of
    the story (GPipe: M; 1f1b: ~S, independent of M).
    """
    m, s = int(num_microbatches), int(n_stages)
    schedule = check_pipeline_schedule(schedule)
    if m < 1 or s < 1:
        raise ValueError(f"need microbatches >= 1 and stages >= 1, got "
                         f"M={m}, S={s}")
    sched = pipeline_schedule_order(m, s, schedule, virtual_stages)
    out = {"microbatches": m, "stages": s, "schedule": schedule,
           "virtual_stages": int(virtual_stages),
           "combined_ticks": sched.makespan,
           "peak_stash_microbatches": sched.peak_stash_microbatches}
    if schedule == "gpipe":
        ticks = m + s - 1
        out.update({"ticks": ticks, "bubble_ticks": s - 1,
                    "bubble_fraction": (s - 1) / ticks})
    else:
        out.update({"ticks": sched.makespan,
                    "bubble_ticks": sched.makespan
                    - 2 * int(virtual_stages) * m,
                    "bubble_fraction": sched.bubble_fraction})
    return out


def simulate_schedule(sched: PipelineSchedule, fwd_s, bwd_s) -> dict:
    """Timed list-scheduling of a generated schedule: every unit keeps
    its device's generated ORDER, starts as soon as its dependencies and
    its device allow, and lasts its device's measured span
    (``fwd_s[d]`` / ``bwd_s[d]`` seconds). Returns the makespan,
    per-device busy seconds, and the resulting bubble fraction — the
    *measured* bubble when the durations come from real per-stage span
    timings (:func:`measure_pipeline_bubble`)."""
    _, _, makespan, busy = _list_schedule(sched.orders, sched.n_chunks,
                                          fwd_s, bwd_s)
    return {"makespan_s": makespan, "busy_s": busy,
            "bubble_fraction":
                1.0 - sum(busy) / (sched.n_stages * makespan)}


def measure_pipeline_bubble(*, n_stages: int = 4, num_microbatches: int = 8,
                            virtual_stages: int = 2, d_model: int = 16,
                            mb_rows: int = 4, layers_per_stage: int = 2,
                            reps: int = 5, seed: int = 0,
                            schedules=PIPELINE_SCHEDULES) -> dict:
    """Measured pipeline bubble fractions from per-stage span timings.

    For each schedule, the per-unit work (one chunk's forward; one
    chunk's recompute+backward — the executor's honest backward cost) is
    built as the real jitted computation at this geometry and timed per
    stage (median of ``reps``, ``jax.device_get`` as the sync point —
    the sanctioned batched readback). The measured spans then drive the
    schedule's dependency graph through :func:`simulate_schedule`: the
    resulting bubble is what the schedule actually costs at the measured
    forward/backward ratio, not the unit-tick formula. (On a single-core
    CPU host the stages cannot physically overlap, so composing measured
    spans through the dependency graph is the honest way to read the
    parallel timeline; on a real mesh the same spans come from the
    per-stage trace.)

    Interleaved chunks hold ``layers_per_stage / virtual_stages`` layers
    each, so their units are measured separately — the comparison keeps
    total work identical across schedules. Returns per-schedule measured
    and modeled bubble fractions plus the raw spans.
    """
    import time as _time

    import numpy as np

    s, m, v = int(n_stages), int(num_microbatches), int(virtual_stages)
    if layers_per_stage % v:
        raise ValueError(f"layers_per_stage {layers_per_stage} not "
                         f"divisible by virtual_stages {v}")
    rng = np.random.default_rng(seed)
    x0 = jnp.asarray(rng.standard_normal((mb_rows, d_model))
                     .astype(np.float32))
    cot0 = jnp.asarray(rng.standard_normal((mb_rows, d_model))
                       .astype(np.float32))

    def _unit_fns(n_layers):
        params = [
            {"w": jnp.asarray((rng.standard_normal((d_model, d_model))
                               / np.sqrt(d_model)).astype(np.float32)),
             "b": jnp.zeros((d_model,), jnp.float32)}
            for _ in range(n_layers)]
        stacked = stack_layer_params(params)

        def chunk(p, h):
            def body(h, lp):
                return jnp.tanh(h @ lp["w"] + lp["b"]), None
            h, _ = jax.lax.scan(body, h, p)
            return h

        fwd = jax.jit(lambda h: chunk(stacked, h))

        def bwd(h, cot):
            y, vjp = jax.vjp(lambda p, hh: chunk(p, hh), stacked, h)
            return vjp(cot)
        return fwd, jax.jit(bwd)

    def _median_span(fn, *args):
        jax.device_get(jax.tree.leaves(fn(*args))[0])   # compile + warm
        spans = []
        for _ in range(max(int(reps), 1)):
            t0 = _time.perf_counter()
            out = fn(*args)
            jax.device_get(jax.tree.leaves(out)[0])
            spans.append(_time.perf_counter() - t0)
        return float(np.median(spans))

    spans_by_v: dict = {}
    for vv in sorted({1} | ({v} if "interleaved_1f1b" in schedules
                            else set())):
        fwd, bwd = _unit_fns(layers_per_stage // vv)
        tf_raw = [_median_span(fwd, x0) for _ in range(s)]
        tb_raw = [_median_span(bwd, x0, cot0) for _ in range(s)]
        # the stages are IDENTICAL computations (one SPMD program), so
        # per-stage sampling noise is not real heterogeneity — the
        # schedule is timed at the cross-stage median span (raw samples
        # reported); a genuinely imbalanced pipeline would feed its real
        # per-stage spans straight into simulate_schedule instead
        # (host floats throughout — the device sync happened inside
        # _median_span's device_get)
        tf = [sorted(tf_raw)[s // 2]] * s
        tb = [sorted(tb_raw)[s // 2]] * s
        spans_by_v[vv] = (tf, tb, tf_raw, tb_raw)

    out = {"n_stages": s, "num_microbatches": m, "virtual_stages": v,
           "geometry": f"d{d_model} mb{mb_rows} "
                       f"L{layers_per_stage}/stage", "schedules": {}}
    for name in schedules:
        vv = v if name == "interleaved_1f1b" else 1
        tf, tb, tf_raw, tb_raw = spans_by_v[vv]
        sched = pipeline_schedule_order(m, s, name, vv)
        sim = simulate_schedule(sched, tf, tb)
        out["schedules"][name] = {
            "measured_bubble_fraction": sim["bubble_fraction"],
            "modeled_bubble_fraction": pipeline_schedule_stats(
                m, s, name, virtual_stages=vv)["bubble_fraction"],
            "makespan_s": sim["makespan_s"],
            "fwd_span_s": tf[0], "bwd_span_s": tb[0],
            "fwd_span_samples_s": tf_raw, "bwd_span_samples_s": tb_raw,
            "virtual_stages": vv,
        }
    return out


# ---------------------------------------------------------------------------
# forward-only GPipe apply (the original construction, kept as-is)
# ---------------------------------------------------------------------------

def _local_stack_apply(layer_apply, local_params, x):
    """Run this stage's L/S stacked layers in sequence via lax.scan."""

    def body(h, layer_p):
        return layer_apply(layer_p, h), None

    y, _ = jax.lax.scan(body, x, local_params)
    return y


def pipeline_apply(layer_apply, stacked_params, x, *,
                   num_microbatches: int, axis: str = "model",
                   mesh: Mesh | None = None, data_axis: str | None = None,
                   with_stats: bool = False):
    """Apply L stacked identical layers to ``x`` through an S-stage
    pipeline over mesh ``axis``.

    ``layer_apply(layer_params, h) -> h`` is one layer's pure function;
    ``stacked_params`` leaves have leading dim L (see
    ``stack_layer_params``); L must divide by the axis size S, the batch
    by ``num_microbatches``. Differentiable end-to-end; returns the same
    result as serially applying the L layers (up to float order).

    ``data_axis`` composes the pipeline with data parallelism: the batch
    dim shards over that mesh axis and each data-parallel row of the mesh
    runs its own fill-drain pipeline over its batch shard (params stay
    pipeline-sharded, replicated across ``data_axis``).
    ``num_microbatches`` must then divide the per-row batch shard.

    ``with_stats=True`` returns ``(y, stats)`` where ``stats`` is
    :func:`pipeline_schedule_stats` for this run's (M, S) — the
    schedule's fill-drain bubble fraction (S-1)/(M+S-1) reported
    instead of hidden (tests/test_pipeline_parallel.py pins it).
    """
    mesh = mesh or get_mesh()
    s = mesh.shape[axis]
    n_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_layers % s:
        raise ValueError(f"{n_layers} layers not divisible by "
                         f"{s} pipeline stages")
    batch = x.shape[0]
    if data_axis is not None:
        d = mesh.shape[data_axis]
        if batch % d:
            raise ValueError(f"batch {batch} not divisible by "
                             f"data axis {d}")
        batch = batch // d           # per-row shard seen inside the body
    if batch % num_microbatches:
        raise ValueError(f"batch {batch} not divisible by "
                         f"{num_microbatches} microbatches")
    mb = batch // num_microbatches
    m = num_microbatches

    def body(local_params, xb):
        # local_params leaves: (L/S, ...) — this stage's layer block
        stage = jax.lax.axis_index(axis)
        mbs = xb.reshape((m, mb) + xb.shape[1:])
        perm = [(i, (i + 1) % s) for i in range(s)]  # downstream hop

        def tick(state, t):
            # lax.scan keeps the program size constant in M and S —
            # a Python unroll doubled the jaxpr per extra microbatch
            carry, out = state
            # stage 0 injects microbatch t; others take the upstream hop
            feed = jnp.take(mbs, jnp.minimum(t, m - 1), axis=0)
            h = jnp.where(stage == 0, feed, carry)
            y = _local_stack_apply(layer_apply, local_params, h)
            # the LAST stage finished microbatch t-(s-1) this tick
            oi = t - (s - 1)
            valid = (stage == (s - 1)) & (oi >= 0)
            slot = jnp.clip(oi, 0, m - 1)
            out = out.at[slot].set(
                jnp.where(valid, y, jnp.take(out, slot, axis=0)))
            carry = jax.lax.ppermute(y, axis, perm)
            return (carry, out), None

        init = (jnp.zeros_like(mbs[0]), jnp.zeros_like(mbs))
        (_, out), _ = jax.lax.scan(tick, init,
                                   jnp.arange(m + s - 1))
        # outputs are populated only on the last stage; psum replicates
        # them (zeros elsewhere keep the sum exact)
        out = jax.lax.psum(out, axis)
        return out.reshape((batch,) + out.shape[2:])

    pspec = jax.tree.map(lambda _: P(axis), stacked_params)
    xspec = P() if data_axis is None else P(data_axis)
    y = shard_map(
        body, mesh=mesh,
        in_specs=(pspec, xspec),
        out_specs=xspec,
        check_rep=False)(stacked_params, x)
    if with_stats:
        return y, pipeline_schedule_stats(m, s)
    return y


# ---------------------------------------------------------------------------
# production path: stage partitioning + the 1F1B train step construction
# ---------------------------------------------------------------------------

def partition_sequential(model, n_stages: int, virtual_stages: int = 1):
    """Validate a ``Sequential`` model for the pipeline path and return
    ``(template, n_layers, layers_per_chunk)``.

    The model's top-level children are the pipeline's layers: they must
    be structurally identical (same param tree structure, leaf shapes
    and dtypes — one SPMD program runs every stage) and stateless (a
    BatchNorm-style running stat cannot be updated consistently while
    microbatches are in flight on different stages). The layer count
    must divide by ``n_stages * virtual_stages``.
    """
    from bigdl_tpu.nn.containers import Sequential
    if not isinstance(model, Sequential):
        raise ValueError(
            f"pipeline_stages needs a Sequential model whose top-level "
            f"children are the pipeline layers, got "
            f"{type(model).__name__}")
    n_layers = len(model.modules)
    chunks = int(n_stages) * int(virtual_stages)
    if n_layers == 0 or n_layers % chunks:
        raise ValueError(
            f"{n_layers} top-level blocks not divisible by "
            f"{n_stages} stages x {virtual_stages} virtual stages")
    if model.params is None:
        raise ValueError("materialize() the model before pipelining")
    p0 = model.params["0"]
    struct0 = jax.tree.structure(p0)
    shapes0 = [(l.shape, jnp.dtype(l.dtype)) for l in jax.tree.leaves(p0)]
    for i in range(1, n_layers):
        pi = model.params[str(i)]
        if jax.tree.structure(pi) != struct0 or \
                [(l.shape, jnp.dtype(l.dtype))
                 for l in jax.tree.leaves(pi)] != shapes0:
            raise ValueError(
                f"pipeline stages must be structurally identical "
                f"blocks: child {i} differs from child 0 — wrap "
                "heterogeneous head/tail layers outside the pipelined "
                "stack")
    if jax.tree.leaves(model.state):
        raise ValueError(
            "pipeline_stages requires stateless blocks (running "
            "statistics like BatchNorm cannot be updated consistently "
            "while microbatches are in flight on different stages) — "
            "use LayerNorm-style normalization")
    return model.modules[0], n_layers, n_layers // chunks


class PipelineParallel:
    """Mechanics of the pipelined train step for one (mesh, model,
    criterion, optimizer) tuple: stage partitioning and parameter
    layout, state import/export (the checkpoint seam), and the combined
    forward/backward schedule step. ``DistriOptimizer`` owns the
    training loop; this class owns the layout and schedule algebra.

    Parameter layout: the L top-level blocks' params are stacked on a
    leading layer axis and PERMUTED device-major (device d's chunks
    contiguous, chunk-major within a device), then sharded over the
    ``pipe`` mesh axis — each device holds exactly its
    ``virtual_stages`` chunks of ``layers_per_chunk`` layers. Optimizer
    state rides the same layout (or per-stage bucket slices under the
    sharded-update composition), so checkpoints export back to the
    params-shaped model tree.
    """

    def __init__(self, mesh, model, criterion, optim, *,
                 n_stages: int, num_microbatches: int,
                 schedule: str = "1f1b", virtual_stages: int = 1,
                 axis: str = "pipe", data_axis: str | None = None,
                 remat_policy: str = "none",
                 sharded_update: bool = False,
                 bucket_mb: float | None = None):
        self.mesh = mesh
        self.axis = axis
        if axis not in mesh.axis_names:
            raise ValueError(
                f"pipeline_stages needs a {axis!r} mesh axis — build the "
                f"mesh with Engine.init(axes={{'data': N, {axis!r}: S}}) "
                f"(mesh has {mesh.axis_names})")
        self.s = int(mesh.shape[axis])
        if self.s != int(n_stages):
            raise ValueError(
                f"pipeline_stages={n_stages} but mesh axis {axis!r} has "
                f"size {self.s}")
        self.v = int(virtual_stages)
        self.schedule = check_pipeline_schedule(schedule)
        if self.schedule == "gpipe" and self.v != 1:
            raise ValueError("virtual_stages > 1 requires the "
                             "'interleaved_1f1b' schedule")
        self.m = int(num_microbatches)
        self.data_axis = (data_axis if data_axis in mesh.axis_names
                          else None)
        self.dp = (int(mesh.shape[self.data_axis])
                   if self.data_axis else 1)
        self.model = model
        self.criterion = criterion
        self.optim = optim
        self.remat_policy = remat_policy
        self.template, self.n_layers, self.lc = partition_sequential(
            model, self.s, self.v)
        # momentum/accumulator leaves carry mesh shardings on this path:
        # the concat-grouped small-leaf update miscompiles under GSPMD
        # (see SGD.group_small_leaves) — force the per-leaf form
        if getattr(optim, "group_small_leaves", False):
            optim.group_small_leaves = False
        for what in ("learning_rates", "weight_decays"):
            if getattr(optim, what, None) is not None:
                raise ValueError(
                    f"pipeline_stages stacks block params on a layer "
                    f"axis, so a params-shaped {what} tree cannot be "
                    "matched leafwise — use scalar hyperparameters")
        # device-major permutation: global stacked row order is
        # [device 0's chunks' layers, device 1's, ...] so a P('pipe')
        # sharding of the leading dim hands each device its own chunks
        self.perm = [g * self.lc + j
                     for d in range(self.s)
                     for cl in range(self.v)
                     for g in [cl * self.s + d]
                     for j in range(self.lc)]
        self.inv_perm = [0] * self.n_layers
        for pos, src in enumerate(self.perm):
            self.inv_perm[src] = pos
        self.sched = pipeline_schedule_order(self.m, self.s,
                                             self.schedule, self.v)
        self.repl = NamedSharding(mesh, P())
        self.stacked_shard = NamedSharding(mesh, P(axis))
        self._gather_jit = None
        self._export_jit = None
        # sharded-update composition: per-STAGE buckets over the local
        # stacked tree (identical across stages — reverse-topological
        # leaf order within the stage is preserved by GradientBuckets),
        # reduce-scattered over the data axis inside the step
        self.su_buckets = None
        if sharded_update:
            if self.data_axis is None or self.dp < 2:
                logger.info(
                    "pipeline + shard_weight_update: no data axis (or "
                    "size 1) on the mesh — nothing to shard the update "
                    "over, running the plain per-stage update")
            else:
                from bigdl_tpu.parameters.all_reduce import \
                    GradientBuckets
                if bucket_mb is None:
                    from bigdl_tpu.optim.sharded_update import \
                        tuned_bucket_mb
                    n_params = sum(
                        int(l.size) for l in jax.tree.leaves(model.params)
                    ) // self.s
                    bucket_mb = tuned_bucket_mb(n_params, self.dp)
                self.su_buckets = GradientBuckets(
                    self._local_template(),
                    bucket_bytes=int(float(bucket_mb) * (1 << 20)),
                    n_shards=self.dp)

    # ------------------------------------------------------------------
    # parameter / optimizer-state layout (the checkpoint seam)
    # ------------------------------------------------------------------
    def _stack(self, child_tree):
        """{'0': t0, ...} -> stacked tree, rows in device-major order."""
        return jax.tree.map(
            lambda *ls: jnp.stack([ls[i] for i in self.perm]),
            *[child_tree[str(i)] for i in range(self.n_layers)])

    def _local_template(self):
        """ShapeDtypeStructs of one device's local stacked tree."""
        return jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(
                (self.v * self.lc,) + tuple(l.shape),
                jnp.dtype(l.dtype)),
            self.model.params["0"])

    def import_params(self, child_tree):
        return jax.device_put(self._stack(child_tree),
                              self.stacked_shard)

    def params_sharding(self):
        return self.stacked_shard

    def _unstack(self, stacked):
        """Stacked (device-major) tree -> {'0': t0, ...} child tree."""
        return {str(i): jax.tree.map(
            lambda l, pos=self.inv_perm[i]: l[pos], stacked)
            for i in range(self.n_layers)}

    def gather_params(self, stacked):
        """Step params state -> the model's per-child tree (for eval,
        ``model.sync`` and checkpoints)."""
        if self._gather_jit is None:
            def gather(st):
                full = jax.tree.map(
                    lambda l: jax.lax.with_sharding_constraint(
                        l, self.repl), st)
                return self._unstack(full)
            self._gather_jit = jax.jit(gather)
        return self._gather_jit(stacked)

    def _state_spec(self, st: dict) -> dict:
        pstruct = jax.tree.structure(self.model.params["0"])
        out = {}
        for k, v in st.items():
            if isinstance(v, dict) and k == "_su":
                out[k] = {bk: P((self.axis, self.data_axis))
                          for bk in v}
            elif isinstance(v, dict) and \
                    jax.tree.structure(v) == pstruct:
                out[k] = jax.tree.map(lambda _: P(self.axis), v)
            else:
                out[k] = P()
        return out

    def opt_state_sharding(self, st: dict) -> dict:
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), self._state_spec(st),
            is_leaf=lambda s: isinstance(s, P))

    def import_opt_state(self, tree_state: dict) -> dict:
        """Params-shaped optimizer state (fresh ``init_state`` on the
        model tree, or a checkpoint) -> the step's stacked (or, under
        the sharded-update composition, per-stage bucket-slice)
        layout."""
        pstruct = jax.tree.structure(self.model.params)
        out = {}
        for k, val in tree_state.items():
            if k == "_su":   # already in step layout (warm re-import)
                out[k] = val
                continue
            if isinstance(val, dict) and \
                    jax.tree.structure(val) == pstruct:
                stacked = self._stack(val)
                if self.su_buckets is not None:
                    # per-stage flatten on the host, concatenated in
                    # device order: global vector (S * padded,), sharded
                    # over (pipe, data) — each device holds its stage's
                    # data-slice of every bucket
                    flats = {bk: [] for bk in self.su_buckets.keys}
                    for d in range(self.s):
                        local = jax.tree.map(
                            lambda l: l[d * self.v * self.lc:
                                        (d + 1) * self.v * self.lc],
                            stacked)
                        for bk, vec in \
                                self.su_buckets.flatten(local).items():
                            flats[bk].append(vec)
                    out.setdefault("_su", {})
                    for bk, parts in flats.items():
                        out["_su"][f"{k}.{bk}"] = jax.device_put(
                            jnp.concatenate(parts),
                            NamedSharding(self.mesh,
                                          P((self.axis,
                                             self.data_axis))))
                else:
                    out[k] = jax.device_put(stacked, self.stacked_shard)
            else:
                out[k] = jax.device_put(jnp.asarray(val), self.repl)
        return out

    def export_opt_state(self, st: dict) -> dict:
        """Step-layout optimizer state -> params-shaped trees (the
        ZeRO-1-compatible checkpoint layout shared with the rest of the
        stack); scalars pass through."""
        # ONE batched readback for the whole state tree (the export
        # runs at checkpoint/sync time, never in the step loop)
        host = jax.device_get(st)
        out = {}
        su = host.get("_su")
        for k, val in host.items():
            if k == "_su":
                continue
            out[k] = self._unstack(val) if isinstance(val, dict) else val
        if su is not None:
            # regroup {state_key.bucket: (S*padded,)} -> params-shaped
            by_state: dict = {}
            for name, vec in su.items():
                sk, bk = name.rsplit(".", 1)
                by_state.setdefault(sk, {})[bk] = vec
            for sk, bks in by_state.items():
                stages = []
                for d in range(self.s):
                    local = self.su_buckets.unflatten({
                        bk: vec.reshape(self.s, -1)[d]
                        for bk, vec in bks.items()})
                    stages.append(local)
                stacked = jax.tree.map(
                    lambda *ls: jnp.concatenate(
                        [jnp.asarray(l) for l in ls]), *stages)
                out[sk] = self._unstack(stacked)
        return out

    # ------------------------------------------------------------------
    # the pipelined train step
    # ------------------------------------------------------------------
    def _tick_tables(self):
        """Static (T, S) int32 schedule tables for the executor scan:
        this device's scheduled forward/backward unit per tick (local
        chunk + microbatch, -1 when idle) and the incoming activation /
        cotangent message's destination slot (written the tick AFTER the
        neighbor produced it — ppermute hops between ticks)."""
        import numpy as np

        T, s = self.sched.makespan, self.s
        fc = -np.ones((T, s), np.int32)
        fm = -np.ones((T, s), np.int32)
        bc = -np.ones((T, s), np.int32)
        bm = -np.ones((T, s), np.int32)
        ifc = -np.ones((T, s), np.int32)
        ifm = -np.ones((T, s), np.int32)
        ibc = -np.ones((T, s), np.int32)
        ibm = -np.ones((T, s), np.int32)
        c = self.sched.n_chunks
        for (kind, g, mb), t in self.sched.starts.items():
            d = g % s
            cl = g // s
            if kind == "F":
                fc[t, d], fm[t, d] = cl, mb
                if g + 1 < c and t + 1 < T:
                    dn = (g + 1) % s
                    ifc[t + 1, dn] = (g + 1) // s
                    ifm[t + 1, dn] = mb
            else:
                bc[t, d], bm[t, d] = cl, mb
                if g > 0 and t + 1 < T:
                    up = (g - 1) % s
                    ibc[t + 1, up] = (g - 1) // s
                    ibm[t + 1, up] = mb
        return tuple(jnp.asarray(a)
                     for a in (fc, fm, bc, bm, ifc, ifm, ibc, ibm))

    def _chunk_body(self, rng_mb):
        """One chunk's forward at microbatch key ``rng_mb``: scans the
        chunk's layers through the (stateless) template with the SAME
        per-child rng folds as ``Sequential.apply`` — dropout draws land
        exactly where the non-pipelined step's do."""
        from bigdl_tpu.nn.module import _fold

        template, lc = self.template, self.lc
        state0 = self.model.state["0"]
        policy = self.remat_policy

        def layer(h, xs):
            lp, gl = xs
            y, _ = template.apply(lp, state0, h, training=True,
                                  rng=_fold(rng_mb, gl))
            return y, None

        if policy == "per_block":
            layer = jax.checkpoint(layer)
        elif policy in ("dots_saveable", "nothing_saveable"):
            from bigdl_tpu.optim.remat import _checkpoint_policy
            layer = jax.checkpoint(layer, policy=_checkpoint_policy(policy))

        def chunk(p_chunk, x, g_global):
            # p_chunk leaves: (Lc, ...) — this chunk's layer block;
            # global child indices g_global*Lc .. +Lc-1 drive the folds
            gls = g_global * lc + jnp.arange(lc, dtype=jnp.int32)
            y, _ = jax.lax.scan(layer, x, (p_chunk, gls))
            return y

        return chunk

    def make_train_step(self, *, grad_clip=None, input_transform=None):
        """Build ``step(params, mstate, opt_state, rng, data, labels,
        epoch) -> (params, mstate, opt_state, loss)`` — one compiled
        program scanning the combined forward/backward schedule.

        Per tick each stage deposits the neighbor hops that arrived,
        runs its scheduled forward unit (chunk input from the bounded
        activation stash; stage 0 injects the strided microbatch), runs
        its scheduled backward unit (recompute-from-stash + ``jax.vjp``,
        the last chunk seeding the cotangent from the criterion — so
        per-unit activation memory never exceeds the schedule's exact
        stash bound), accumulates gradients and the loss numerator in
        donated scan carries, and ppermutes the activation/cotangent
        hops. After the scan the optimizer update — plain per-stage, or
        the per-stage bucketed reduce-scatter + 1/N update + all-gather
        over the data axis under the sharded-update composition — fires
        exactly ONCE per accumulated step, preserving
        ``set_grad_accumulation``'s contract.
        """
        ax, s, v, lc, m = self.axis, self.s, self.v, self.lc, self.m
        c = self.sched.n_chunks
        W_a, W_c = self.sched.act_window, self.sched.cot_window
        tables = self._tick_tables()
        criterion = self.criterion
        size_avg = getattr(criterion, "size_average", True)
        data_axis, dp = self.data_axis, self.dp
        su_buckets, optim = self.su_buckets, self.optim
        chunk_of = self._chunk_body

        def body(p_loc, mstate, st, key, d_loc, l_loc, epoch):
            from bigdl_tpu.optim.accumulation import split_microbatches
            stage = jax.lax.axis_index(ax)
            # input_transform runs per microbatch, like the
            # accumulation path: the widened batch is never
            # materialized whole
            ds = split_microbatches(d_loc, m)
            ls = split_microbatches(l_loc, m)
            mb_sd = jax.eval_shape(
                (input_transform or (lambda a: a)),
                jax.ShapeDtypeStruct(ds.shape[1:], ds.dtype))
            # the activation stash and cotangent inbox, indexed
            # [chunk_local, mb % window]; zeros are harmless — every
            # read is schedule-gated
            acts = jnp.zeros((v, W_a) + mb_sd.shape, mb_sd.dtype)
            cots = jnp.zeros((v, W_c) + mb_sd.shape, jnp.float32)
            gacc = jax.tree.map(jnp.zeros_like, p_loc)
            fmsg = jnp.zeros(mb_sd.shape, mb_sd.dtype)
            bmsg = jnp.zeros(mb_sd.shape, jnp.float32)
            num0 = jnp.zeros((), jnp.float32)

            def chunk_rows(tree, cl):
                return jax.tree.map(
                    lambda l: jax.lax.dynamic_slice_in_dim(
                        l, cl * lc, lc, 0), tree)

            def tick(carry, xs):
                acts, cots, gacc, num, fmsg, bmsg = carry
                fc, fm, bc, bm, ifc, ifm, ibc, ibm = \
                    (jnp.take(row, stage) for row in xs)
                # 1) deposit last tick's neighbor hops into their slots
                ci, si = jnp.clip(ifc, 0, v - 1), \
                    jnp.clip(ifm, 0, m - 1) % W_a
                acts = acts.at[ci, si].set(
                    jnp.where(ifc >= 0, fmsg.astype(acts.dtype),
                              acts[ci, si]))
                ci, si = jnp.clip(ibc, 0, v - 1), \
                    jnp.clip(ibm, 0, m - 1) % W_c
                cots = cots.at[ci, si].set(
                    jnp.where(ibc >= 0, bmsg, cots[ci, si]))

                # 2) forward unit
                fcl = jnp.clip(fc, 0, v - 1)
                fmb = jnp.clip(fm, 0, m - 1)
                g_glob_f = fcl * s + stage

                def do_fwd(_):
                    x_data = jax.lax.dynamic_index_in_dim(
                        ds, fmb, 0, keepdims=False)
                    if input_transform is not None:
                        x_data = input_transform(x_data)
                    x_in = jnp.where(g_glob_f == 0,
                                     x_data.astype(acts.dtype),
                                     acts[fcl, fmb % W_a])
                    chunk = chunk_of(jax.random.fold_in(key, fmb))
                    y = chunk(chunk_rows(p_loc, fcl), x_in, g_glob_f)
                    return y.astype(acts.dtype), x_in

                def no_fwd(_):
                    z = jnp.zeros(mb_sd.shape, acts.dtype)
                    return z, z

                y_f, x_f = jax.lax.cond(fc >= 0, do_fwd, no_fwd, None)
                # stash the consumed input for the backward recompute
                # (chunk 0's input came from the data, not the inbox)
                acts = acts.at[fcl, fmb % W_a].set(
                    jnp.where(fc >= 0, x_f, acts[fcl, fmb % W_a]))

                # 3) backward unit: recompute-from-stash + vjp; the
                # last chunk seeds its cotangent from the criterion
                bcl = jnp.clip(bc, 0, v - 1)
                bmb = jnp.clip(bm, 0, m - 1)
                g_glob_b = bcl * s + stage

                def do_bwd(_):
                    x_in = acts[bcl, bmb % W_a]
                    chunk = chunk_of(jax.random.fold_in(key, bmb))
                    y, vjp_fn = jax.vjp(
                        lambda pc, xx: chunk(pc, xx, g_glob_b),
                        chunk_rows(p_loc, bcl), x_in)
                    lb = jax.lax.dynamic_index_in_dim(
                        ls, bmb, 0, keepdims=False)
                    lossv, cvjp = jax.vjp(
                        lambda yy: criterion.apply(yy, lb), y)
                    cot_loss = cvjp(jnp.ones_like(lossv))[0]
                    is_last = g_glob_b == c - 1
                    cot_y = jnp.where(is_last,
                                      cot_loss.astype(jnp.float32),
                                      cots[bcl, bmb % W_c])
                    gp, gx = vjp_fn(cot_y.astype(y.dtype))
                    return (gp, gx.astype(jnp.float32),
                            jnp.where(is_last,
                                      lossv.astype(jnp.float32), 0.0))

                def no_bwd(_):
                    return (jax.tree.map(
                        lambda l: jnp.zeros((lc,) + l.shape[1:],
                                            l.dtype), p_loc),
                        jnp.zeros(mb_sd.shape, jnp.float32),
                        jnp.zeros((), jnp.float32))

                gp, gx, lossv = jax.lax.cond(bc >= 0, do_bwd, no_bwd,
                                             None)
                gacc = jax.tree.map(
                    lambda acc, g: jax.lax.dynamic_update_slice_in_dim(
                        acc,
                        jax.lax.dynamic_slice_in_dim(
                            acc, bcl * lc, lc, 0) + g,
                        bcl * lc, 0),
                    gacc, gp)
                num = num + lossv

                # 4) neighbor hops for the next tick
                down = [(i, (i + 1) % s) for i in range(s)]
                up = [(i, (i - 1) % s) for i in range(s)]
                fmsg = jax.lax.ppermute(y_f, ax, down)
                bmsg = jax.lax.ppermute(gx, ax, up)
                return (acts, cots, gacc, num, fmsg, bmsg), None

            init = (acts, cots, gacc, num0, fmsg, bmsg)
            (_, _, grads, num, _, _), _ = jax.lax.scan(tick, init,
                                                       tables)
            # only the device owning the last chunk accumulated loss
            num = jax.lax.psum(num, ax)
            if size_avg:
                num = num / m
                grads = jax.tree.map(lambda g: g / m, grads)
            if data_axis is not None:
                num = jax.lax.pmean(num, data_axis)
            st = dict(st, epoch=epoch)
            if su_buckets is None:
                if data_axis is not None:
                    grads = jax.lax.pmean(grads, data_axis)
                grads = _clip_local(grads, grad_clip, (ax,))
                new_p, new_st = optim.update(grads, p_loc, st)
            else:
                new_p, new_st = _stage_sharded_update(
                    su_buckets, optim, grads, p_loc, st,
                    data_axis=data_axis, n_data=dp, pipe_axis=ax,
                    grad_clip=grad_clip)
            return new_p, mstate, new_st, num

        mesh = self.mesh
        pspec = P(self.axis)
        dspec = P(self.data_axis) if self.data_axis else P()

        def step(params, mstate, opt_state, rng, data, labels, epoch,
                 n_valid=None):
            if n_valid is not None:
                raise ValueError(
                    "pipeline_stages does not compose with "
                    "pad_partial_batches — pad in the dataset pipeline")
            from bigdl_tpu.optim.accumulation import \
                validate_microbatches
            rows = (data.shape[0] // self.dp if self.data_axis
                    else data.shape[0])
            validate_microbatches(rows, m, what="per-shard batch")
            # blocks must map activations shape/dtype-identically —
            # the stash and the neighbor hops are one uniform buffer
            mb_sd0 = jax.ShapeDtypeStruct(
                (rows // m,) + tuple(data.shape[1:]), data.dtype)
            if input_transform is not None:
                mb_sd0 = jax.eval_shape(input_transform, mb_sd0)
            out_sd = jax.eval_shape(
                lambda p, x: self.template.apply(
                    p, self.model.state["0"], x, training=False)[0],
                self.model.params["0"], mb_sd0)
            if (tuple(out_sd.shape) != tuple(mb_sd0.shape)
                    or out_sd.dtype != mb_sd0.dtype):
                raise ValueError(
                    f"pipeline blocks must preserve the activation "
                    f"shape/dtype (got {mb_sd0.shape}/{mb_sd0.dtype} -> "
                    f"{out_sd.shape}/{out_sd.dtype})")
            sspec = self._state_spec(opt_state)
            return shard_map(
                body, mesh=mesh,
                in_specs=(pspec, P(), sspec, P(), dspec, dspec, P()),
                out_specs=(pspec, P(), sspec, P()),
                check_rep=False)(params, mstate, opt_state, rng, data,
                                 labels, epoch)

        return step


def _clip_local(grads, clip, psum_axes) -> dict:
    """Gradient clipping on the stage-local domain: the global L2 norm
    is a ``psum`` of per-stage square sums over the pipe axis (stages
    hold disjoint parameters, so the sum IS the whole-model norm)."""
    if not clip:
        return grads
    if clip["min_value"] is not None:
        grads = jax.tree.map(
            lambda g: jnp.clip(g, clip["min_value"], clip["max_value"]),
            grads)
    if clip["l2_norm"] is not None:
        local = sum(jnp.sum(jnp.square(g))
                    for g in jax.tree.leaves(grads))
        norm = jnp.sqrt(jax.lax.psum(local, psum_axes))
        scale = jnp.minimum(1.0, clip["l2_norm"] / (norm + 1e-12))
        grads = jax.tree.map(lambda g: g * scale, grads)
    return grads


def _stage_sharded_update(buckets, optim, grads, params, st, *,
                          data_axis, n_data, pipe_axis, grad_clip):
    """The sharded-update composition inside the pipeline body
    (arXiv:2004.13336 per stage): flatten this stage's gradients into
    its reverse-order buckets, ``psum_scatter`` each over the data axis
    (the bucketed reduce-scatter — reverse-topological order within the
    stage is preserved, so earlier buckets' collectives can overlap the
    schedule's remaining backward units), update the 1/N parameter and
    optimizer-state slices, and all-gather the updated parameters."""
    fg = buckets.flatten(grads)
    fp = buckets.flatten(params)
    idx = jax.lax.axis_index(data_axis)
    g_sl, p_sl = {}, {}
    for bk in buckets.keys:
        slen = buckets.padded_sizes[bk] // n_data
        g_sl[bk] = jax.lax.psum_scatter(
            fg[bk], data_axis, scatter_dimension=0, tiled=True) / n_data
        p_sl[bk] = jax.lax.dynamic_slice_in_dim(fp[bk], idx * slen,
                                                slen, 0)
    g_sl = _clip_local(g_sl, grad_clip, (pipe_axis, data_axis))
    su = st.pop("_su", {})
    st_sl = dict(st)
    by_state: dict = {}
    for name, vec in su.items():
        sk, bk = name.rsplit(".", 1)
        by_state.setdefault(sk, {})[bk] = vec
    for sk, bks in by_state.items():
        st_sl[sk] = bks
    new_p_sl, new_st_sl = optim.update(g_sl, p_sl, st_sl)
    new_fp = {bk: jax.lax.all_gather(new_p_sl[bk], data_axis,
                                     tiled=True)
              for bk in buckets.keys}
    new_st = {k: v for k, v in new_st_sl.items()
              if k not in by_state}
    new_su = {}
    for sk in by_state:
        for bk, vec in new_st_sl[sk].items():
            new_su[f"{sk}.{bk}"] = vec
    if new_su or su:
        new_st["_su"] = new_su
    return buckets.unflatten(new_fp), new_st

"""Distributed runtime: mesh engine, shardings, collectives (replaces the
reference's Engine thread pools + Spark BlockManager parameter server)."""

from bigdl_tpu.parallel.engine import (Engine, get_mesh, data_sharding,
                                       replicated)
from bigdl_tpu.parallel.sequence import (dot_product_attention,
                                         ring_attention,
                                         ring_attention_sharded,
                                         ulysses_attention)
from bigdl_tpu.parallel.pipeline import pipeline_apply, stack_layer_params
from bigdl_tpu.parallel.expert import moe_apply

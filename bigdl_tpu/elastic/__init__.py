"""Elastic training: async checkpointing, mesh-portable resume, restart.

ROADMAP item 1 rendered as a subsystem (docs/ELASTICITY.md): training
runs were bit-exact to checkpoint but mesh-shape-bound — losing a host
(or gaining chips) meant a lost run. The three pillars here turn a
preemption into a resize:

- :mod:`~bigdl_tpu.elastic.checkpoint_writer` — ``CheckpointWriter``
  runs checkpoint serialization on a background worker thread; the
  training loop pays one packed ``jax.device_get``
  (``snapshot_to_host``) and hands off.
- :mod:`~bigdl_tpu.elastic.manifest` — the versioned, manifest-carrying
  checkpoint format (logical leaf shapes/dtypes + mesh descriptor;
  manifest committed last, so ``latest_checkpoint`` never sees a torn
  snapshot), plus :mod:`~bigdl_tpu.elastic.redistribute` placing the
  saved host arrays onto ANY target mesh (arXiv:2112.01075's portable
  arrays, applied to checkpoints).
- :mod:`~bigdl_tpu.elastic.runner` — ``ElasticRunner`` supervises a
  training child, watches the ``training_liveness`` health check, and
  on death/wedge dumps a flight-recorder postmortem and respawns from
  the latest manifest.

HOST-ONLY CONTRACT (jaxlint JX5): every module here lazy-imports jax —
the supervisor and manifest tooling must run with no device runtime.
"""
from bigdl_tpu.elastic.checkpoint_writer import (CheckpointWriter,
                                                 snapshot_to_host)
from bigdl_tpu.elastic.manifest import (MANIFEST_FORMAT, MANIFEST_VERSION,
                                        build_manifest, latest_checkpoint,
                                        manifest_name, mesh_layout,
                                        read_manifest, sweep_checkpoints,
                                        validate_tree, write_manifest)
from bigdl_tpu.elastic.redistribute import describe_layout, redistribute
from bigdl_tpu.elastic.runner import (ElasticRunner, ProcessChild,
                                      probe_liveness)

__all__ = ["CheckpointWriter", "ElasticRunner", "MANIFEST_FORMAT",
           "MANIFEST_VERSION", "ProcessChild", "build_manifest",
           "describe_layout", "latest_checkpoint", "load_checkpoint",
           "manifest_name", "mesh_layout", "probe_liveness",
           "read_manifest", "redistribute", "snapshot_to_host",
           "sweep_checkpoints", "validate_tree", "write_manifest"]


def _member_path(dir_path: str, name: str) -> str:
    if "://" in str(dir_path):
        return f"{dir_path}/{name}"
    import os
    return os.path.join(dir_path, name)


def load_checkpoint(path: str, *, neval: int | None = None,
                    validate: bool = True):
    """Load one complete checkpoint from ``path``: ``(model, state,
    manifest)``. ``neval=None`` picks the newest manifest; an explicit
    ``neval`` loads that snapshot. ``state`` is the full training-state
    dict the optimizers save (driver counters, opt_state, rng, data
    position, ``mesh_layout``) — hand it to ``Optimizer.set_state`` and
    the run resumes on WHATEVER mesh the new process initializes
    (``redistribute`` does the placement). ``validate`` checks every
    loaded leaf against the manifest's recorded shapes/dtypes."""
    if neval is None:
        man = latest_checkpoint(path)
        if man is None:
            raise FileNotFoundError(
                f"no complete checkpoint manifest under {path!r} — "
                "nothing to resume from (was the checkpoint written by "
                "a pre-elastic build? see docs/ELASTICITY.md)")
    else:
        man = None
        for name in (manifest_name(f".{int(neval)}"), manifest_name("")):
            try:
                man = read_manifest(_member_path(path, name))
            except (FileNotFoundError, OSError):
                continue
            if int(man["neval"]) == int(neval):
                break
            man = None
        if man is None:
            raise FileNotFoundError(
                f"no checkpoint manifest for neval={neval} under "
                f"{path!r}")
    from bigdl_tpu.utils import file as _file
    model = _file.load_module(_member_path(path, man["model"]))
    state = _file.load(_member_path(path, man["state"]))
    if validate:
        validate_tree(model.params, man.get("params"), "params")
        validate_tree(state.get("opt_state"), man.get("opt_state"),
                      "optimizer state")
    return model, state, man

"""Mesh-portable placement: put saved host arrays onto ANY target mesh.

The checkpoint holds host-global numpy (``snapshot_to_host`` /
``utils.file._to_host`` allgather before writing), so a resume is pure
placement — there is no data transform between mesh shapes. What this
module adds over a bare ``device_put`` is the elastic bookkeeping: it
reads the manifest's saved mesh layout, logs the resize (8 devices →
4 devices is a routine event, not an anomaly), and routes every leaf
through the right placement primitive for the current topology:

- single-controller (the common case, and all CPU test meshes):
  ``jax.device_put`` with the target sharding — XLA splits the host
  array across the new device set directly.
- multi-process meshes: ``jax.make_array_from_callback`` assembles each
  global array from per-shard numpy slices — the host-global
  generalization of ``make_array_from_process_local_data`` (which wants
  a per-process LOCAL shard; a checkpoint restore holds the GLOBAL
  value on every process). True multi-host redistribution beyond a
  single controller (per-process partial reads) is a documented
  leftover in ROADMAP item 1.

Bit-exactness across the resize comes from the layers below: batch
order and RNG replay are mesh-independent (dataset position state +
host-RNG snapshot in the checkpoint), and reductions use the same
deterministic tree order regardless of device count — pinned by
tests/test_elastic.py on 8→4 and 4→8 CPU meshes.

HOST-ONLY CONTRACT (jaxlint JX5): jax is imported lazily inside the
placement functions only.
"""
from __future__ import annotations

import logging

__all__ = ["describe_layout", "redistribute"]

logger = logging.getLogger("bigdl_tpu.elastic")


def describe_layout(layout) -> dict | None:
    """Normalize a mesh descriptor to ``{axis_name: size}``. Accepts a
    full manifest dict (unwraps its ``"mesh"`` key), a ``mesh_layout``
    dict, or None (layout unknown — e.g. a pre-elastic checkpoint)."""
    if layout is None:
        return None
    if "mesh" in layout and "axis_names" not in layout:
        layout = layout["mesh"]
    if layout is None:
        return None
    return {str(a): int(s) for a, s in
            zip(layout["axis_names"], layout["axis_sizes"])}


def _mesh_axes(mesh) -> dict:
    return {str(a): int(mesh.shape[a]) for a in mesh.axis_names}


def redistribute(tree, src_layout, dst_mesh, *, shardings=None,
                 what: str = "tree"):
    """Place a host tree onto ``dst_mesh`` under ``shardings``.

    ``src_layout`` is the saved mesh descriptor (manifest dict, layout
    dict, or None); when it differs from the target mesh the resize is
    logged. ``shardings`` is a single sharding applied to every leaf or
    a matching tree of shardings; None means fully replicated."""
    if tree is None:
        return None
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    src = describe_layout(src_layout)
    dst = _mesh_axes(dst_mesh)
    if src is not None and src != dst:
        logger.info("elastic resume: redistributing %s from mesh %s "
                    "onto mesh %s", what, src, dst)
    if shardings is None:
        shardings = NamedSharding(dst_mesh, PartitionSpec())
    if jax.process_count() <= 1:
        # single controller: XLA slices the host array per device
        return jax.device_put(tree, shardings)
    # multi-process: every process holds the GLOBAL value (checkpoints
    # store allgathered arrays), so build each jax.Array by handing XLA
    # the numpy slice for whichever shard index it asks for
    def place(leaf, sh):
        arr = np.asarray(leaf)
        return jax.make_array_from_callback(
            arr.shape, sh, lambda idx: arr[idx])

    if hasattr(shardings, "device_set"):  # one sharding for every leaf
        return jax.tree.map(lambda leaf: place(leaf, shardings), tree)
    return jax.tree.map(place, tree, shardings)

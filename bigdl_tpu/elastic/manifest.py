"""Manifest-carrying checkpoint format: the portable half of elastic resume.

A checkpoint written by ``Optimizer.set_checkpoint`` is three files per
trigger fire — ``model<suffix>`` (the pickled host module),
``state<suffix>`` (driver counters + optimizer state + RNG + data
position), and ``manifest<suffix>.json`` (this module). The manifest is
deliberately the LAST file committed: :func:`latest_checkpoint` trusts
only manifests, so a run killed between the model/state writes and the
manifest write simply resumes from the previous complete snapshot —
no torn checkpoint is ever eligible for resume (the per-file
``.tmp`` + atomic-rename staging in ``utils/file.py`` guarantees no
individual file is torn either).

What makes the format mesh-portable (arXiv:2112.01075's portable-array
idea rendered on checkpoints): the manifest records the LOGICAL leaf
layout — flattened keypath -> shape + dtype for params and optimizer
state — plus the mesh descriptor the arrays were saved under (axis
names, sizes, device kinds; deliberately NOT device ids, matching the
AOT cache key's elastic-restart stance, tuning/aot_cache.py
``mesh_descriptor``). The arrays themselves are host-global numpy, so
resuming on a different mesh is validation + placement
(``redistribute``), never a data transform.

HOST-ONLY CONTRACT (jaxlint JX5): no module-level jax import — manifest
reading/listing must work in supervisors (``ElasticRunner``) that never
initialize a device runtime. jax is imported lazily only inside the
functions that flatten live trees.
"""
from __future__ import annotations

import json
import logging
import os
import re

logger = logging.getLogger("bigdl_tpu.elastic")

__all__ = ["MANIFEST_FORMAT", "MANIFEST_VERSION", "build_manifest",
           "latest_checkpoint", "manifest_name", "mesh_layout",
           "read_manifest", "sweep_checkpoints", "validate_tree",
           "write_manifest"]

MANIFEST_FORMAT = "bigdl_tpu.elastic.manifest"
MANIFEST_VERSION = 1

_MANIFEST_RE = re.compile(r"^manifest(\.\d+)?\.json$")


def manifest_name(suffix: str = "") -> str:
    """``manifest<suffix>.json`` — suffix matches the model/state files
    (``""`` under ``overwrite_checkpoint``, ``.<neval>`` otherwise)."""
    return f"manifest{suffix}.json"


def mesh_layout(mesh) -> dict | None:
    """JSON-able mesh descriptor: axis names + sizes + device kinds.
    Device ids are deliberately excluded — the descriptor must compare
    equal across restarts that land on different physical hosts."""
    if mesh is None:
        return None
    kinds = sorted({str(getattr(d, "device_kind", d.platform))
                    for d in mesh.devices.flat})
    return {"axis_names": [str(a) for a in mesh.axis_names],
            "axis_sizes": [int(mesh.shape[a]) for a in mesh.axis_names],
            "device_kinds": kinds}


def _leaf_specs(tree) -> dict:
    """Flattened keypath -> {shape, dtype} for every array leaf (opaque
    leaves — bytes, strings — are recorded by type name only)."""
    if tree is None:
        return {}
    import jax
    import numpy as np
    specs: dict = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        key = jax.tree_util.keystr(path) or "<root>"
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            specs[key] = {"shape": [int(s) for s in leaf.shape],
                          "dtype": str(np.dtype(leaf.dtype))}
        elif np.isscalar(leaf) and not isinstance(leaf, (str, bytes)):
            # a bare python number — device/numpy scalars carry
            # shape+dtype and took the branch above
            specs[key] = {"shape": [],
                          "dtype": str(np.dtype(type(leaf)))}
        else:
            specs[key] = {"opaque": type(leaf).__name__}
    return specs


def build_manifest(*, neval: int, epoch: int, model_file: str,
                   state_file: str, params=None, opt_state=None,
                   mesh=None, extra: dict | None = None) -> dict:
    """Assemble the manifest dict for one checkpoint snapshot. The
    params/opt_state trees must already be HOST trees (the async
    writer's snapshot) — building a manifest must never read a device
    value."""
    man = {
        "format": MANIFEST_FORMAT,
        "version": MANIFEST_VERSION,
        "neval": int(neval),
        "epoch": int(epoch),
        "model": str(model_file),
        "state": str(state_file),
        "mesh": mesh_layout(mesh) if not isinstance(mesh, dict) else mesh,
        "params": _leaf_specs(params),
        "opt_state": _leaf_specs(opt_state),
    }
    if extra:
        man["extra"] = dict(extra)
    return man


def write_manifest(manifest: dict, path: str) -> None:
    """Atomic manifest write (temp name + rename via the checkpoint IO
    staging, utils/file.py) — a crash mid-write never leaves a torn
    manifest that :func:`latest_checkpoint` would trust."""
    from bigdl_tpu.utils.file import _open_write_atomic
    body = json.dumps(manifest, indent=2, sort_keys=True).encode()
    with _open_write_atomic(path) as f:
        f.write(body)


def read_manifest(path: str) -> dict:
    """Load + sanity-check one manifest file."""
    from bigdl_tpu.utils.file import _open_read
    with _open_read(path) as f:
        man = json.loads(f.read().decode())
    if man.get("format") != MANIFEST_FORMAT:
        raise ValueError(f"{path} is not an elastic checkpoint manifest "
                         f"(format={man.get('format')!r})")
    if int(man.get("version", -1)) > MANIFEST_VERSION:
        raise ValueError(
            f"{path} is manifest version {man['version']}, newer than "
            f"this build understands ({MANIFEST_VERSION}) — upgrade "
            "before resuming")
    return man


def _list_manifest_names(path: str) -> list[str]:
    from bigdl_tpu.utils.file import _fs_for, _is_url
    if _is_url(path):
        fs = _fs_for(path)
        try:
            names = [str(n).rsplit("/", 1)[-1]
                     for n in fs.ls(path, detail=False)]
        except FileNotFoundError:
            return []
    else:
        try:
            names = os.listdir(path)
        except (FileNotFoundError, NotADirectoryError):
            return []
    return sorted(n for n in names if _MANIFEST_RE.match(n))


def latest_checkpoint(path: str, *, cache: dict | None = None) \
        -> dict | None:
    """The newest COMPLETE checkpoint under ``path``: scan manifests,
    skip unreadable/torn ones with a warning, return the highest-neval
    manifest (or None when the directory holds no complete snapshot —
    a fresh start, not an error: the elastic runner's first attempt
    and a post-crash resume share this call.

    ``cache`` is the polling fast path: pass the SAME caller-owned dict
    on every call (the weight publisher polls every few seconds) and a
    manifest is re-read/re-parsed only when its mtime+size changed —
    the atomic-rename commit always bumps both, and a torn/unreadable
    verdict is re-tested on change too. Entries for deleted manifests
    are dropped. Local filesystems only; URL paths always re-read."""
    best = None
    seen = set()
    for name in _list_manifest_names(path):
        is_url = "://" in str(path)
        full = f"{path}/{name}" if is_url else os.path.join(path, name)
        seen.add(name)
        sig = None
        if cache is not None and not is_url:
            try:
                st = os.stat(full)
                sig = (st.st_mtime_ns, st.st_size)
            except OSError:
                sig = None
            if sig is not None:
                hit = cache.get(name)
                if hit is not None and hit[0] == sig:
                    man = hit[1]          # parsed (or None: torn)
                    if man is not None and (
                            best is None
                            or int(man["neval"]) > int(best["neval"])):
                        best = man
                    continue
        try:
            man = read_manifest(full)
        except Exception as e:
            logger.warning("skipping unreadable checkpoint manifest "
                           "%s: %s", full, e)
            man = None
        if cache is not None and sig is not None:
            cache[name] = (sig, man)
        if man is not None and (best is None
                                or int(man["neval"]) > int(best["neval"])):
            best = man
    if cache is not None:
        for stale in set(cache) - seen:
            del cache[stale]
    return best


_MEMBER_RE = re.compile(r"^(model|state)(\.\d+)?$")
_SWEEP_RE = re.compile(
    r"^(?:(?:model|state)(\.\d+)?|manifest(\.\d+)?\.json)(?:\.tmp)?$")


def _list_names(path: str) -> list[str]:
    from bigdl_tpu.utils.file import _fs_for, _is_url
    if _is_url(path):
        fs = _fs_for(path)
        try:
            return sorted(str(n).rsplit("/", 1)[-1]
                          for n in fs.ls(path, detail=False))
        except FileNotFoundError:
            return []
    try:
        return sorted(os.listdir(path))
    except (FileNotFoundError, NotADirectoryError):
        return []


def _remove(path: str) -> None:
    from bigdl_tpu.utils.file import _fs_for, _is_url
    if _is_url(path):
        _fs_for(path).rm(path)
    else:
        os.remove(path)


def sweep_checkpoints(path: str, keep: int) -> dict:
    """Retention GC for a NUMBERED-suffix checkpoint directory
    (``set_checkpoint(..., keep=K)``; ROADMAP 1(c)): keep the newest
    ``keep`` complete checkpoints by ``neval``, delete the older
    manifest+model+state triples, and sweep debris a crash can leave
    behind — member files whose manifest never committed (the write
    order makes them unreachable), manifests that no longer parse, and
    leftover ``.tmp`` staging files.

    Only files this format names (``model.N`` / ``state.N`` /
    ``manifest.N.json`` and their ``.tmp`` stages) are ever touched;
    unsuffixed overwrite-mode files and anything else in the directory
    are left alone. Single-writer contract: call from the checkpoint
    writer (the optimizer runs it on the async writer thread right
    after the manifest commit), never concurrently with a write.
    Returns ``{"kept": [neval...], "removed": [names...]}``."""
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    names = _list_names(path)

    def full(name: str) -> str:
        return (f"{path}/{name}" if "://" in str(path)
                else os.path.join(path, name))

    complete: dict[str, int] = {}       # numbered suffix -> neval
    torn_manifests: list[str] = []
    for name in names:
        m = _MANIFEST_RE.match(name)
        if not m or m.group(1) is None:   # unsuffixed: overwrite mode
            continue
        try:
            complete[m.group(1)] = int(read_manifest(full(name))["neval"])
        except Exception as e:
            logger.warning("checkpoint GC: sweeping unreadable manifest "
                           "%s: %s", name, e)
            torn_manifests.append(name)
    keep_suffixes = {s for s, _ in sorted(complete.items(),
                                          key=lambda kv: kv[1])[-keep:]}
    removed = []
    for name in names:
        m = _SWEEP_RE.match(name)
        if not m:
            continue                       # not ours
        if name.endswith(".tmp"):
            doomed = True                  # abandoned staging file
        elif name in torn_manifests:
            doomed = True
        else:
            suffix = m.group(1) or m.group(2)
            if suffix is None:
                continue                   # unsuffixed: never touched
            doomed = suffix not in keep_suffixes
        if doomed:
            try:
                _remove(full(name))
                removed.append(name)
            except Exception as e:         # never fail the writer
                logger.warning("checkpoint GC: could not remove %s: %s",
                               name, e)
    kept = sorted(complete[s] for s in keep_suffixes)
    if removed:
        logger.info("checkpoint GC: kept neval %s, removed %d files",
                    kept, len(removed))
    return {"kept": kept, "removed": removed}


def validate_tree(tree, specs: dict | None, what: str) -> None:
    """Leaf-by-leaf shape/dtype validation of a loaded tree against the
    manifest's recorded layout — the guard that turns silent shape drift
    (a truncated file, a changed model) into one clear error before any
    device placement happens."""
    if specs is None:
        return
    got = _leaf_specs(tree)
    problems = []
    for key in sorted(set(specs) | set(got)):
        want_spec, got_spec = specs.get(key), got.get(key)
        if want_spec is None:
            problems.append(f"{key}: not in manifest")
        elif got_spec is None:
            problems.append(f"{key}: missing from loaded {what}")
        elif want_spec != got_spec:
            problems.append(f"{key}: manifest {want_spec} != loaded "
                            f"{got_spec}")
        if len(problems) >= 5:
            problems.append("...")
            break
    if problems:
        raise ValueError(
            f"loaded {what} does not match the checkpoint manifest "
            f"({len(problems)} mismatches): " + "; ".join(problems))

"""Async checkpoint writer: serialization off the training critical path.

The synchronous ``Optimizer._checkpoint`` paid the full save on the
training thread: device_get, clone, pickle, zip, rename — all while the
device pipeline drained (the train step donates its inputs, so nothing
can dispatch until the host owns the values anyway, but everything
AFTER the readback is pure host work the loop does not need to wait
for). This module splits the save at exactly that line:

- :func:`snapshot_to_host` — the ONE packed ``jax.device_get`` of every
  device leaf across params/opt-state/RNG, issued on the training
  thread (correctness: the next step's ``donate_argnums`` buffers must
  not be rewritten under a pending readback).
- :class:`CheckpointWriter` — a bounded-queue daemon worker (the
  dataset/prefetch.py worker-thread pattern on the save side) that runs
  the serialize + atomic-rename job in the background while training
  dispatches ahead. ``submit`` hands off; ``barrier`` waits the queue
  dry (epoch end, exit); ``close`` drains and joins. Worker exceptions
  are stored and re-raised at the next submit/barrier — a failed save
  must fail the run, not vanish into a dead thread.

The handoff/write split is exported as the ``elastic_ckpt_save_overhead``
receipt: ``handoff_s`` is what the critical path still pays (snapshot +
enqueue), ``write_s`` is what moved to the worker, and their ratio is
the receipt the bench row and tests pin.

HOST-ONLY CONTRACT (jaxlint JX5): no module-level jax import — the
queue/thread machinery is importable with no device runtime; jax is
lazily imported only inside :func:`snapshot_to_host`.
"""
from __future__ import annotations

import logging
import queue
import threading
import time

from bigdl_tpu.observability.registry import default_registry

__all__ = ["CheckpointWriter", "snapshot_to_host"]

logger = logging.getLogger("bigdl_tpu.elastic")


def snapshot_to_host(tree):
    """Copy every device leaf of ``tree`` to host numpy with one packed
    ``jax.device_get`` (single transfer program, not a per-leaf sync).
    Non-addressable leaves (multi-host shards) are allgathered first so
    the snapshot always holds global arrays — same contract as
    ``utils.file._to_host``, minus the per-leaf transfers."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree.flatten(tree)
    device_idx = [i for i, l in enumerate(leaves)
                  if isinstance(l, jax.Array)]
    gathered = []
    for i in device_idx:
        leaf = leaves[i]
        if not leaf.is_fully_addressable:
            from jax.experimental import multihost_utils
            leaf = multihost_utils.process_allgather(leaf, tiled=True)
        gathered.append(leaf)
    host = jax.device_get(gathered)
    for i, arr in zip(device_idx, host):
        leaves[i] = np.asarray(arr)
    return jax.tree.unflatten(treedef, leaves)


class CheckpointWriter:
    """Bounded-queue background checkpoint writer.

    One daemon worker runs submitted save jobs strictly in submission
    order (overwrite-mode checkpoints depend on it: the newest snapshot
    must land last). ``depth`` bounds how many snapshots can be pending
    in host memory at once — a slow filesystem backpressures ``submit``
    instead of accumulating unbounded host copies.

    Observability: ``elastic_ckpt_pending`` gauge (snapshots queued or
    writing), ``elastic_ckpt_saves_total`` counter, and the
    ``elastic_ckpt_save_overhead`` gauge holding the last save's
    background write seconds — the cost the critical path no longer
    pays. :meth:`receipt` aggregates the same split per run.
    """

    def __init__(self, *, name: str = "ckpt", depth: int = 2,
                 timeout: float = 120.0):
        if depth < 1:
            raise ValueError(f"writer depth must be >= 1, got {depth}")
        self._name = name
        self._timeout = timeout
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._cond = threading.Condition()
        self._pending = 0
        self._error: BaseException | None = None
        self._closed = False
        self._saves = 0
        self._handoff_s = 0.0
        self._write_s = 0.0
        reg = default_registry()
        self._pending_gauge = reg.gauge(
            "elastic_ckpt_pending",
            "checkpoint snapshots queued or being written",
            labelnames=("writer",))
        self._overhead_gauge = reg.gauge(
            "elastic_ckpt_save_overhead",
            "seconds of checkpoint serialization moved off the critical "
            "path by the last async save", labelnames=("writer",))
        self._saves_total = reg.counter(
            "elastic_ckpt_saves_total",
            "checkpoint snapshots committed by the async writer",
            labelnames=("writer",))
        self._worker = threading.Thread(
            target=self._work, name=f"ckpt-writer:{name}", daemon=True)
        self._worker.start()

    # -- worker side --
    def _work(self):
        while not self._stop.is_set():
            try:
                job, label = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            t0 = time.perf_counter()
            try:
                job()
            except BaseException as e:
                with self._cond:
                    if self._error is None:
                        self._error = e
                logger.exception("async checkpoint save %r failed", label)
            else:
                dt = time.perf_counter() - t0
                with self._cond:
                    self._saves += 1
                    self._write_s += dt
                self._saves_total.inc(writer=self._name)
                self._overhead_gauge.set(dt, writer=self._name)
            finally:
                with self._cond:
                    self._pending -= 1
                    self._pending_gauge.set(self._pending,
                                            writer=self._name)
                    self._cond.notify_all()

    # -- training-thread side --
    def submit(self, job, *, label: str = "", handoff_s: float = 0.0):
        """Queue one save job (a zero-arg callable over host-only data).
        Raises the first stored worker error — a checkpoint that failed
        in the background surfaces on the training thread at the next
        fire, before the run can outlive its last good snapshot."""
        with self._cond:
            if self._error is not None:
                err, self._error = self._error, None
                raise RuntimeError(
                    f"async checkpoint save failed in the background "
                    f"(writer '{self._name}')") from err
            if self._closed:
                raise RuntimeError(
                    f"checkpoint writer '{self._name}' is closed")
            self._handoff_s += handoff_s
            # count BEFORE the job is visible to the worker, else a fast
            # write could decrement first and barrier would see 0 early
            self._pending += 1
            self._pending_gauge.set(self._pending, writer=self._name)
        try:
            self._q.put((job, label), timeout=self._timeout)
        except queue.Full:
            with self._cond:
                self._pending -= 1
                self._pending_gauge.set(self._pending, writer=self._name)
                self._cond.notify_all()
            raise RuntimeError(
                f"checkpoint writer '{self._name}' queue stayed full for "
                f"{self._timeout}s — the save job is wedged")

    def barrier(self, timeout: float | None = None):
        """Block until every submitted save has committed (epoch end /
        exit ordering: the epoch-boundary shuffle and the final return
        must not race a write in flight). Re-raises a stored worker
        error once drained."""
        deadline = self._timeout if timeout is None else timeout
        with self._cond:
            if not self._cond.wait_for(lambda: self._pending == 0,
                                       timeout=deadline):
                raise RuntimeError(
                    f"checkpoint writer '{self._name}' still has "
                    f"{self._pending} pending saves after {deadline}s — "
                    "the save job is wedged")
            if self._error is not None:
                err, self._error = self._error, None
                raise RuntimeError(
                    f"async checkpoint save failed in the background "
                    f"(writer '{self._name}')") from err

    def close(self, timeout: float | None = None):
        """Drain, stop, join. Idempotent; raises if the worker refuses
        to die (a wedged save should be loud, not silent)."""
        if self._closed:
            return
        self._closed = True
        try:
            self.barrier(timeout=timeout)
        finally:
            self._stop.set()
            self._worker.join(timeout=10.0)
        if self._worker.is_alive():
            raise RuntimeError(
                f"checkpoint writer '{self._name}' did not stop — "
                "save job is wedged")

    @property
    def pending(self) -> int:
        with self._cond:
            return self._pending

    def receipt(self) -> dict:
        """The save-overhead receipt: seconds the critical path paid
        (``handoff_s``) vs seconds moved to the worker (``write_s``)."""
        with self._cond:
            handoff, write = self._handoff_s, self._write_s
            total = handoff + write
            return {
                "saves": self._saves,
                "handoff_s": handoff,
                "write_s": write,
                "off_critical_path_fraction":
                    (write / total) if total > 0 else 0.0,
            }

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

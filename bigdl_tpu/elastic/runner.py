"""Detect-and-restart supervision: turn a wedged run into a resize.

Bench rounds 4–5 of the fleet runs were zeroed by one failure class: a
training process whose backend wedged — alive by PID, dead by
progress. The observability stack already detects exactly this (the
``training_liveness`` health check flips ``/healthz`` to 503 when no
step completes within the liveness deadline) and already captures the
evidence (``FlightRecorder.dump_postmortem``). This module closes the
loop: :class:`ElasticRunner` supervises a training CHILD PROCESS,
polls child exit + liveness, and on death or wedge dumps a postmortem,
tears the child down, and respawns it resuming from the latest
checkpoint manifest — on whatever mesh the surviving hardware gives it
(the manifest + ``redistribute`` make the mesh shape a resume-time
choice, and the AOT cache key deliberately ignores device ids, so a
same-shape restart steps warm).

The runner is deliberately process-granular: a wedged XLA runtime
cannot be repaired in-process, and a full process teardown is the only
reliable way to release a held TPU. The child is any script that calls
``Optimizer.set_checkpoint`` (async manifest-writing saves) and
``set_metrics_server`` (liveness endpoint); the runner needs nothing
else from it.

HOST-ONLY CONTRACT (jaxlint JX5): the supervisor never imports jax —
it must run on a coordinator host with no device runtime at all.
"""
from __future__ import annotations

import logging
import os
import subprocess
import time
import urllib.error
import urllib.request

from bigdl_tpu.elastic.manifest import latest_checkpoint
from bigdl_tpu.observability.registry import default_registry

__all__ = ["ElasticRunner", "ProcessChild", "probe_liveness"]

logger = logging.getLogger("bigdl_tpu.elastic")


def probe_liveness(url: str, *, checks: str = "training_liveness",
                   timeout: float = 2.0):
    """One ``/healthz?check=`` probe. Returns ``(ok, detail)`` where
    ``ok`` is True (healthy), False (the server answered 503 — wedged),
    or None (unknown: unreachable or an unexpected status; while the
    process is alive an unreachable server usually just means the
    metrics port is not up yet, so unknown is NOT treated as wedged)."""
    probe = f"{url.rstrip('/')}/healthz?check={checks}"
    try:
        with urllib.request.urlopen(probe, timeout=timeout) as resp:
            if resp.status == 200:
                return True, "ok"
            return None, f"unexpected status {resp.status}"
    except urllib.error.HTTPError as e:
        if e.code == 503:
            try:
                detail = e.read().decode(errors="replace")[:200]
            except Exception:
                detail = ""
            return False, detail or "healthz returned 503"
        return None, f"unexpected status {e.code}"
    except Exception as e:
        return None, f"unreachable: {e}"


class ProcessChild:
    """A training attempt as a subprocess. The default child factory —
    tests substitute scripted fakes with the same poll()/kill() face."""

    def __init__(self, argv, *, env=None, cwd=None, stdout=None,
                 stderr=None):
        self._proc = subprocess.Popen(
            argv, env=env, cwd=cwd, stdout=stdout, stderr=stderr)

    @property
    def pid(self) -> int:
        return self._proc.pid

    def poll(self):
        """Exit code, or None while running."""
        return self._proc.poll()

    def kill(self):
        """Hard teardown — a wedged runtime does not honor SIGTERM."""
        try:
            self._proc.kill()
            self._proc.wait(timeout=10.0)
        except Exception:
            logger.warning("could not reap child pid %s", self.pid,
                           exc_info=True)


class ElasticRunner:
    """Supervision loop: spawn → watch (exit code + liveness) → on
    failure postmortem + teardown + respawn from the latest manifest.

    ``spawn(resume_manifest, attempt)`` builds one training attempt and
    returns a child handle (``pid``/``poll()``/``kill()``, e.g.
    :class:`ProcessChild`); ``resume_manifest`` is the newest complete
    checkpoint under ``checkpoint_dir`` or None for a cold start — the
    child decides how to consume it (typically ``elastic.
    load_checkpoint`` + ``Optimizer.set_state``). ``liveness`` is the
    child's metrics-server base URL (or a callable returning
    ``(ok, detail)``); None disables wedge detection and supervises
    exit codes only.

    Restarts are counted on the ``elastic_restarts_total`` counter and
    capped by ``max_restarts`` — a run that cannot hold a liveness
    deadline for N attempts is broken, not unlucky, and the postmortem
    directories hold the evidence for each attempt.
    """

    def __init__(self, spawn, checkpoint_dir: str, *,
                 max_restarts: int = 3, poll_interval: float = 0.5,
                 liveness=None, postmortem_dir: str | None = None,
                 name: str = "elastic"):
        self._spawn = spawn
        self._dir = checkpoint_dir
        self._max_restarts = max_restarts
        self._poll_interval = poll_interval
        self._liveness = liveness
        self._pm_dir = postmortem_dir or os.path.join(
            str(checkpoint_dir), "postmortem")
        self._name = name
        self._restarts = default_registry().counter(
            "elastic_restarts_total",
            "training attempts restarted by the elastic runner",
            labelnames=("runner",))

    def _probe(self):
        if self._liveness is None:
            return None, "liveness probing disabled"
        if callable(self._liveness):
            return self._liveness()
        return probe_liveness(self._liveness)

    def _watch(self, child):
        """Block until the attempt resolves: None on a clean exit,
        otherwise a human-readable failure reason (child already torn
        down)."""
        while True:
            rc = child.poll()
            if rc is not None:
                if rc == 0:
                    return None
                return f"training child died with exit code {rc}"
            ok, detail = self._probe()
            if ok is False:
                child.kill()
                return (f"training child wedged past the liveness "
                        f"deadline ({detail}); killed")
            time.sleep(self._poll_interval)

    def run(self) -> dict:
        """Supervise until one attempt exits cleanly. Returns a summary
        dict; raises RuntimeError after ``max_restarts`` failures."""
        restarts = 0
        postmortems = []
        resumed_from = []
        last_reason = None
        while True:
            resume = latest_checkpoint(self._dir)
            resumed_from.append(
                None if resume is None else int(resume["neval"]))
            logger.info(
                "elastic attempt %d: %s", restarts + 1,
                "cold start" if resume is None else
                f"resuming from neval={resume['neval']} "
                f"(mesh {resume.get('mesh')})")
            child = self._spawn(resume, restarts + 1)
            reason = self._watch(child)
            if reason is None:
                return {"rc": 0, "restarts": restarts,
                        "postmortems": postmortems,
                        "resumed_from": resumed_from}
            last_reason = reason
            postmortems.append(self._postmortem(child, restarts + 1,
                                                reason))
            if restarts >= self._max_restarts:
                raise RuntimeError(
                    f"elastic runner '{self._name}' giving up after "
                    f"{restarts} restarts (last failure: {last_reason}); "
                    f"postmortems under {self._pm_dir}")
            restarts += 1
            self._restarts.inc(runner=self._name)
            logger.warning("elastic restart %d/%d: %s", restarts,
                           self._max_restarts, reason)

    def _postmortem(self, child, attempt: int, reason: str) -> str:
        """Evidence before respawn: a flight-recorder postmortem dump
        per failed attempt (dump_postmortem never raises)."""
        from bigdl_tpu.observability.flight_recorder import FlightRecorder
        rec = FlightRecorder(
            dir=os.path.join(self._pm_dir, f"attempt{attempt}"))
        rec.record("elastic", "child failure", attempt=attempt,
                   reason=reason, pid=getattr(child, "pid", None))
        return rec.dump_postmortem(
            RuntimeError(reason), reason=f"elastic restart: {reason}")

"""On-device gradient accumulation: one compiled step, k microbatches
(ISSUE 10 tentpole).

The per-chip batch is capped by activation memory; gradient accumulation
runs an effectively k-times-larger batch at near-constant peak HBM by
``lax.scan``-ning k microbatches through forward/backward with the
gradient accumulated in the scan carry (donated buffers — XLA updates
the accumulator in place), then running the existing optimizer update
EXACTLY ONCE. Collectives amortize the same way: the gradient
reduce-scatter / all-reduce fires once per ACCUMULATED step, so wire
bytes per example drop by k (pinned statically in
tests/test_accumulation.py against the compiled HLO).

Microbatch layout is STRIDED — microbatch ``j`` takes global rows
``j, j+k, j+2k, ...`` via a free ``(B,) -> (B/k, k) -> (k, B/k)``
reshape/transpose. On a data-sharded mesh each device's contiguous
block splits locally (every microbatch holds ``local_rows/k`` rows from
EVERY device), so the scan never moves batch rows across chips. Which
rows form a microbatch is semantically irrelevant: the accumulated
gradient, the loss average, and the masked numerator/denominator are
sums over all rows regardless of grouping.

Semantics vs the single k×-batch step:

- **loss / gradients** — exact mean semantics are preserved (per-row
  cotangent scale, masked valid-count normalization: numerator and
  denominator accumulate separately and divide once). Results are
  bit-identical whenever the float additions involved are exact, and
  within partial-sum rounding (~1 ulp per reduction) otherwise —
  splitting a reduction into k partial sums is a re-association, which
  f32 addition does not commute with (docs/PERFORMANCE.md pins both:
  bitwise on an exactly-representable workload, tight tolerance on
  real models).
- **RNG** — each microbatch draws from ``fold_in(step_rng, j)``
  (deterministic, replayable); a dropout model's mask SEQUENCE therefore
  differs from the k×-batch step's single draw, by design.
- **batch statistics** — BN-style state is computed per microbatch and
  averaged across the k microbatches (inexact leaves; integer counters
  pass through), mirroring the per-shard ``pmean`` of the explicit
  sharded step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["split_microbatches", "microbatch_valid_mask",
           "validate_microbatches", "accumulated_value_and_grads",
           "finalize_accumulated", "make_train_step"]


def validate_microbatches(batch: int, k: int, *, what: str = "batch"):
    """Loud divisibility contract: the scan needs k equal microbatches."""
    k = int(k)
    if k < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {k}")
    if batch % k != 0:
        raise ValueError(
            f"grad accumulation: {what} {batch} is not divisible by "
            f"num_microbatches={k} — choose k | {what} (microbatches "
            "must be equal-sized for exact loss averaging)")
    return k


def split_microbatches(x, k: int):
    """``(B, ...) -> (k, B/k, ...)`` strided view: microbatch ``j`` is
    rows ``j::k``. Free on a dim-0-sharded array — each device's block
    reshapes locally, no cross-chip row movement."""
    b = x.shape[0]
    validate_microbatches(b, k)
    m = b // k
    return jnp.moveaxis(x.reshape((m, k) + x.shape[1:]), 1, 0)


def microbatch_valid_mask(j, m: int, k: int, n_valid):
    """Validity mask for microbatch ``j`` of a padded batch: row ``i``
    of the microbatch is global row ``i*k + j``, valid while below the
    batch's real row count (``MaskedCriterion`` contract)."""
    return (jnp.arange(m) * k + j) < n_valid


def accumulated_value_and_grads(mb_value_and_grad, k: int, params,
                                data, labels, rng):
    """Scan ``k`` microbatches through forward/backward, accumulating
    gradients (and the loss numerator/denominator) in the scan carry.

    ``mb_value_and_grad(params, j, data_mb, labels_mb, key) ->
    ((num, weight, new_mstate), grads)`` is one microbatch's
    value-and-grad: ``num``/``weight`` are the caller's loss numerator
    and denominator contributions (see :func:`finalize_accumulated`),
    ``new_mstate`` the microbatch's module-state update.

    Returns ``(num_sum, weight_sum, mstate, grads_sum)`` — gradients
    and loss UNNORMALIZED (the caller divides once), module state
    averaged across microbatches (inexact leaves; others take the last
    microbatch's value, which is identical across microbatches for
    step counters since every microbatch starts from the same state).
    """
    ds = split_microbatches(data, k)
    ls = split_microbatches(labels, k)
    js = jnp.arange(k, dtype=jnp.int32)

    def run_one(p, j, d, l):
        key = jax.random.fold_in(rng, j) if rng is not None else None
        return mb_value_and_grad(p, j, d, l, key)

    # trace-time shape probe: the carry needs zeros of the grads/state/
    # loss structure before the first microbatch runs (no unrolled
    # first iteration — the scan body is the WHOLE program, compile
    # time and code size stay flat in k)
    out_shapes = jax.eval_shape(run_one, params, js[0], ds[0], ls[0])
    (num_s, w_s, ms_s), g_s = out_shapes
    zeros = lambda tree: jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), tree)

    def body(carry, xs):
        j, d, l = xs
        gacc, nacc, wacc, msacc = carry
        (num, w, ms), g = run_one(params, j, d, l)
        gacc = jax.tree.map(jnp.add, gacc, g)
        msacc = jax.tree.map(
            lambda acc, cur: acc + cur / k
            if jnp.issubdtype(cur.dtype, jnp.inexact) else cur,
            msacc, ms)
        return (gacc, nacc + num, wacc + w, msacc), None

    init = (zeros(g_s), zeros(num_s), zeros(w_s), zeros(ms_s))
    (grads, num, weight, mstate), _ = jax.lax.scan(body, init,
                                                   (js, ds, ls))
    return num, weight, mstate, grads


def finalize_accumulated(num, weight, grads, *, k: int,
                         size_average: bool, masked: bool):
    """Normalize the accumulated loss and gradients to the single
    k×-batch step's semantics.

    - unmasked, size-averaging criterion: each microbatch contributed
      its own normalized mean (``num`` = sum of k means, ``weight``
      unused) — divide by k; equal microbatches make this the exact
      full-batch mean.
    - unmasked, summing criterion: sums add; no normalization.
    - masked: each microbatch contributed the UNNORMALIZED masked sum
      and its valid count; one division by the total count reproduces
      the full batch's masked mean exactly (per-microbatch counts may
      differ — normalizing early would be wrong).
    """
    if masked and size_average:
        denom = jnp.maximum(weight, 1.0)
    elif not masked and size_average:
        denom = jnp.asarray(float(k), num.dtype)
    else:
        denom = None
    if denom is None:
        return num, grads
    return num / denom, jax.tree.map(lambda g: g / denom, grads)


def make_train_step(*, fwd, criterion, masked=None, input_transform=None,
                    grad_clip=None, update_fn, num_microbatches: int = 1,
                    aux_loss=None):
    """Construct the train step both optimizers compile:
    ``step(params, mstate, opt_state, rng, data, labels, epoch,
    n_valid=None) -> (params, mstate, opt_state, loss)``.

    ``fwd`` is the (possibly remat-wrapped) model forward
    (optim/remat.py), ``update_fn(grads, params, opt_state) ->
    (new_params, new_opt_state)`` the optimizer update (the sharded
    update's ``apply_update`` on that path), ``masked`` the
    ``MaskedCriterion`` when partial-batch padding is on. ``aux_loss``
    (``set_expert_parallel``) maps the forward's new module state to an
    auxiliary objective term — the MoE load-balancing loss riding the
    state — added to the criterion (and, under accumulation, averaged
    across microbatches with the rest of the loss).

    ``num_microbatches == 1`` builds EXACTLY the pre-accumulation
    program — same ops in the same order, so golden training fixtures
    and the AOT executable cache are untouched. ``> 1`` scans strided
    microbatches with the gradient accumulated in donated carry
    buffers and runs ``update_fn`` once.
    """
    from bigdl_tpu.optim.optimizer import _clip_gradients
    k = int(num_microbatches)
    use_mask = masked is not None
    if use_mask and aux_loss is not None:
        raise ValueError(
            "expert_parallel's aux loss does not compose with "
            "pad_partial_batches: the masked numerator/denominator "
            "normalization cannot carry the per-microbatch aux term — "
            "disable padding or the aux loss")
    size_avg = getattr(criterion, "size_average", True)

    if k == 1:
        def train_step(params, mstate, opt_state, rng, data, labels,
                       epoch, n_valid=None):
            if input_transform is not None:
                data = input_transform(data)

            def loss_fn(p):
                y, new_mstate = fwd(p, mstate, data, training=True,
                                    rng=rng)
                if use_mask:
                    # validity mask materialized in-step from the real
                    # row count: padded rows contribute exactly zero to
                    # loss and gradient (nn.MaskedCriterion)
                    mask = jnp.arange(data.shape[0]) < n_valid
                    return masked.apply(y, labels, mask), new_mstate
                loss = criterion.apply(y, labels)
                if aux_loss is not None:
                    loss = loss + aux_loss(new_mstate)
                return loss, new_mstate

            (loss, new_mstate), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = _clip_gradients(grads, grad_clip)
            opt_state = dict(opt_state, epoch=epoch)
            new_params, new_opt_state = update_fn(grads, params,
                                                  opt_state)
            return new_params, new_mstate, new_opt_state, loss

        return train_step

    def train_step(params, mstate, opt_state, rng, data, labels, epoch,
                   n_valid=None):
        def mb_vag(p, j, d, l, key):
            if input_transform is not None:
                # per-microbatch: the transformed (widened) batch is
                # never materialized whole — transforms are per-row
                # (the u8 normalize path), so the slice commutes
                d = input_transform(d)

            def loss_fn(pp):
                y, new_mstate = fwd(pp, mstate, d, training=True,
                                    rng=key)
                if use_mask:
                    mask = microbatch_valid_mask(j, d.shape[0], k,
                                                 n_valid)
                    num, cnt = masked.masked_sum(y, l, mask)
                else:
                    num = criterion.apply(y, l)
                    if aux_loss is not None:
                        # per-microbatch aux joins the numerator; the
                        # final /k restores its mean like the loss
                        num = num + aux_loss(new_mstate)
                    cnt = jnp.ones((), num.dtype)
                return num, (cnt, new_mstate)

            (num, (cnt, new_mstate)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(p)
            return (num, cnt, new_mstate), grads

        num, w, new_mstate, grads = accumulated_value_and_grads(
            mb_vag, k, params, data, labels, rng)
        loss, grads = finalize_accumulated(num, w, grads, k=k,
                                           size_average=size_avg,
                                           masked=use_mask)
        grads = _clip_gradients(grads, grad_clip)
        opt_state = dict(opt_state, epoch=epoch)
        new_params, new_opt_state = update_fn(grads, params, opt_state)
        return new_params, new_mstate, new_opt_state, loss

    return train_step

"""Standalone model evaluation.

Reference parity: Validator / LocalValidator / DistriValidator
(optim/Validator.scala:51, LocalValidator.scala, DistriValidator.scala:29-80)
— broadcast an eval-mode model, map over the validation set, monoid-reduce
the ValidationResults.
"""
from __future__ import annotations

import jax

from bigdl_tpu.dataset.dataset import AbstractDataSet, to_jax_batch

__all__ = ["Validator", "LocalValidator"]


class LocalValidator:
    """(reference optim/LocalValidator.scala — per-core clones collapse
    into one jitted eval fn)"""

    def __init__(self, model, dataset: AbstractDataSet):
        self.model = model
        self.dataset = dataset

    def test(self, methods):
        model = self.model
        model.materialize()
        model.evaluate()

        @jax.jit
        def eval_apply(params, mstate, data):
            out, _ = model.apply(params, mstate, data, training=False)
            return out

        results = [None] * len(methods)
        for batch in self.dataset.data(train=False):
            data, labels = to_jax_batch(batch)
            out = eval_apply(model.params, model.state, data)
            for i, m in enumerate(methods):
                r = m(out, labels)
                results[i] = r if results[i] is None else results[i] + r
        return list(zip(results, methods))


def Validator(model, dataset: AbstractDataSet):
    """Factory (reference optim/Validator.scala:51 — dispatch on dataset
    type; the sharded eval path reuses LocalValidator per shard)."""
    return LocalValidator(model, dataset)

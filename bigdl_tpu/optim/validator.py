"""Standalone model evaluation.

Reference parity: Validator / LocalValidator / DistriValidator
(optim/Validator.scala:51, LocalValidator.scala, DistriValidator.scala:29-80)
— broadcast an eval-mode model, map over the validation set, monoid-reduce
the ValidationResults.
"""
from __future__ import annotations

import jax
import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet, to_jax_batch
from bigdl_tpu.dataset.prefetch import PrefetchIterator

__all__ = ["Validator", "LocalValidator", "DistriValidator",
           "local_sharded_eval"]


def _eval_batches(dataset: AbstractDataSet, name: str):
    """One evaluation pass with batch assembly prefetched: the worker
    runs the dataset's transform chain while the consumer dispatches
    eval on the previous batch (dataset/prefetch.py — the validators'
    rendering of the train loop's overlapped input pipeline)."""
    return PrefetchIterator(dataset.data(train=False), depth=2,
                            name=name, dataset=dataset)


def _record_validation(summary, results, methods, step: int) -> None:
    """Append each method's scalar to a ValidationSummary event log
    (observability/summary.py), tagged by the method's repr."""
    if summary is None:
        return
    for m, r in zip(methods, results):
        summary.add_scalar(repr(m), float(r.result()[0]), int(step))


class LocalValidator:
    """(reference optim/LocalValidator.scala — per-core clones collapse
    into one jitted eval fn)"""

    def __init__(self, model, dataset: AbstractDataSet):
        self.model = model
        self.dataset = dataset

    def test(self, methods, *, summary=None, step: int = 0):
        """``summary``/``step``: optionally append each method's scalar
        to a ValidationSummary event log at ``step``."""
        model = self.model
        model.materialize()
        model.evaluate()

        @jax.jit
        def eval_apply(params, mstate, data):
            out, _ = model.apply(params, mstate, data, training=False)
            return out

        results = [None] * len(methods)
        with _eval_batches(self.dataset, "local eval") as batches:
            for batch in batches:
                data, labels = to_jax_batch(batch)
                out = eval_apply(model.params, model.state, data)
                for i, m in enumerate(methods):
                    r = m(out, labels)
                    results[i] = r if results[i] is None \
                        else results[i] + r
        _record_validation(summary, results, methods, step)
        return list(zip(results, methods))


def _padded_eval(jit_fn, data_sharding, multiple, params_sharding=None):
    """Shared eval runner: pad batches up to ``multiple``, place on
    ``data_sharding``, trim outputs back (validation sets need not
    divide the mesh — reference DistriValidator.scala:38-78). One home
    for the pad/place/trim logic the single- and multi-host eval paths
    all share (it was triplicated — round-5 review).

    ``params_sharding`` (multi-host paths, where params arrive as HOST
    trees): place params/state once per distinct tree instead of
    re-uploading the whole model every batch. The one-slot cache keys on
    object identity and HOLDS the keyed trees, so their ids cannot be
    recycled while cached.

    CACHING CONTRACT — params trees are immutable: because the cache
    keys on the ROOT objects' identity, a caller that mutates a
    params/mstate tree IN PLACE between calls (same dict, new leaves)
    would silently evaluate against the stale device-placed copies.
    Every current caller passes fresh ``_to_host`` trees per validation
    pass, which satisfies the contract by construction; if you hold a
    tree across calls, treat it as frozen — build a new dict to change
    it."""

    cache = {"key": None, "placed": None}

    def run(params, mstate, data):
        if params_sharding is not None:
            if cache["key"] is None or cache["key"][0] is not params \
                    or cache["key"][1] is not mstate:
                cache["key"] = (params, mstate)
                cache["placed"] = (
                    jax.device_put(params, params_sharding),
                    jax.device_put(mstate, params_sharding))
            params, mstate = cache["placed"]
        data = np.asarray(data)
        n = data.shape[0]
        pad = (-n) % multiple
        if pad:
            data = np.concatenate([data, np.repeat(data[-1:], pad,
                                                   axis=0)])
        return np.asarray(jit_fn(params, mstate,
                                 jax.device_put(data, data_sharding)))[:n]

    return run


def local_sharded_eval(apply_fn):
    """Build an eval runner sharded over THIS process's devices.

    The multi-host evaluation primitive: the global mesh spans
    non-addressable devices, so cross-host validation evaluates each
    process's own shard on its local chips (batch sharded across all of
    them — not just device 0) and monoid-reduces results across hosts.
    ``apply_fn(params, mstate, data) -> out`` must be jit-traceable;
    params/mstate are host (or process-local) trees."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.local_devices()
    mesh = Mesh(np.array(devs), ("ldata",))
    shard = NamedSharding(mesh, P("ldata"))
    repl = NamedSharding(mesh, P())
    jit_fn = jax.jit(apply_fn, in_shardings=(repl, repl, shard),
                     out_shardings=shard)
    return _padded_eval(jit_fn, shard, len(devs), params_sharding=repl)


class DistriValidator:
    """Standalone evaluation over the device mesh (reference
    optim/DistriValidator.scala:29-80 — broadcast eval-mode model, clone
    per core, map-reduce over the rdd).

    TPU-native: params replicated over the mesh, batches sharded along the
    data axis (padded to the mesh multiple and the padding masked out of
    the reduction), ValidationResults monoid-reduced exactly like the
    reference's cross-partition reduce.
    """

    def __init__(self, model, dataset: AbstractDataSet, mesh=None):
        from bigdl_tpu.parallel.engine import (data_sharding, get_mesh,
                                               replicated)
        self.model = model
        self.dataset = dataset
        self.mesh = mesh or get_mesh()
        self._repl = replicated(self.mesh)
        self._shard = data_sharding(self.mesh)
        self._n_shards = int(np.prod(self.mesh.devices.shape))

    def test(self, methods, *, summary=None, step: int = 0):
        """``summary``/``step``: optionally append each method's scalar
        to a ValidationSummary event log at ``step``."""
        if jax.process_count() > 1:
            return self._test_multihost(methods, summary=summary,
                                        step=step)
        model = self.model
        model.materialize()
        model.evaluate()
        params = jax.device_put(model.params, self._repl)
        mstate = jax.device_put(model.state, self._repl)

        @jax.jit
        def eval_apply(p, s, data):
            out, _ = model.apply(p, s, data, training=False)
            return out

        run = _padded_eval(eval_apply, self._shard, self._n_shards)
        results = [None] * len(methods)
        with _eval_batches(self.dataset, "distri eval") as batches:
            for batch in batches:
                out = run(params, mstate, batch.data)
                labels = np.asarray(batch.labels)
                for i, m in enumerate(methods):
                    r = m(out, labels)
                    results[i] = r if results[i] is None \
                        else results[i] + r
        _record_validation(summary, results, methods, step)
        return list(zip(results, methods))

    def _test_multihost(self, methods, *, summary=None, step: int = 0):
        """Multi-host evaluation: each process maps over ITS OWN dataset
        shard on its local devices (the reference's executor-local map),
        then the results monoid-reduce across hosts (the driver reduce,
        DistriValidator.scala:70-80). COLLECTIVE: all processes call
        test() together. Params are host-gathered once (a GSPMD-sharded
        model re-assembles via the same process allgather checkpoints
        use)."""
        from bigdl_tpu.optim.optimizer import _require_process_sharded
        from bigdl_tpu.optim.validation import aggregate_results
        from bigdl_tpu.utils.file import _to_host
        _require_process_sharded(self.dataset, "dataset")
        model = self.model
        model.materialize()
        model.evaluate()
        params = _to_host(model.params)
        mstate = _to_host(model.state)

        def apply_fn(p, s, data):
            out, _ = model.apply(p, s, data, training=False)
            return out

        run = local_sharded_eval(apply_fn)
        results = [None] * len(methods)
        with _eval_batches(self.dataset, "multihost eval") as batches:
            for batch in batches:
                out = run(params, mstate, batch.data)  # numpy; methods
                labels = np.asarray(batch.labels)      # take host arrays
                for i, m in enumerate(methods):
                    r = m(out, labels)
                    results[i] = r if results[i] is None \
                        else results[i] + r
        merged = aggregate_results(results)
        _record_validation(summary, merged, methods, step)
        return list(zip(merged, methods))


def Validator(model, dataset: AbstractDataSet, mesh=None):
    """Factory (reference optim/Validator.scala:51 — dispatch on dataset
    type: sharded datasets / an explicit mesh get the DistriValidator)."""
    if mesh is not None or (hasattr(dataset, "is_sharded")
                            and dataset.is_sharded()):
        return DistriValidator(model, dataset, mesh)
    return LocalValidator(model, dataset)

"""Fully cross-replica-sharded weight update (ROADMAP item 2).

The training path past ZeRO-1: instead of one replicated post-backward
``psum`` plus a replicated optimizer update, the gradient exchange is
decomposed per arXiv:2004.13336 ("Automatic Cross-Replica Sharding of
Weight Update in Data-Parallel Training"):

  reduce-scatter gradients -> each replica updates ONLY its 1/N slice of
  parameters + optimizer state -> all-gather the updated parameters

with gradients partitioned into size-targeted buckets
(``parameters.all_reduce.GradientBuckets`` — reverse-topological leaf
order, so a bucket's collective depends only on its own leaves' backward
segment and XLA's latency-hiding scheduler can issue it while the rest of
the backward still runs, instead of serializing communication after the
full backward).

Two constructions, selected by ``wire_codec``:

- **Implicit** (``wire_codec=None``): the forward/backward stays in
  global view (XLA's induced gradient reduction — bit-identical loss and
  gradients to the replicated path), and only the optimizer update runs
  under ``shard_map``: each shard updates its bucket slices, parameters
  are re-gathered by a replication constraint. Trajectories are
  BIT-IDENTICAL to the replicated update (tests/test_sharded_update.py)
  while optimizer state is stored 1/N per replica and the update math is
  1/N per replica.

- **Explicit** (``wire_codec="fp32" | "bf16" | "int8"``): the whole step
  runs under ``shard_map`` — per-shard forward/backward over the local
  batch shard (the reference's per-partition semantics, including
  per-shard batch statistics merged by ``pmean``), bucketed
  wire-compressed reduce-scatter (``all_to_all`` at codec width + local
  f32 accumulation), sharded update on f32 master slices, and a
  wire-compressed parameter all-gather (the reference's FP16
  ``getWeights``, FP16CompressedTensor.scala:267-275, generalized). The
  ``int8`` codec uses per-destination-slice scales, stochastic rounding
  (unbiased), and an error-feedback residual carried in the optimizer
  state under ``"ef_residual"`` — so it rides checkpoints with the rest
  of the training state.

Checkpoint compatibility: optimizer state is exported through
``GradientBuckets.unflatten`` back to params-shaped trees, so sharded
checkpoints load into replicated/ZeRO-1 runs and vice versa; only the
(layout-bound) error-feedback residual is reset when the bucket geometry
or mesh size changes.
"""
from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.parameters.all_reduce import GradientBuckets
from bigdl_tpu.parameters.compression import get_codec
from bigdl_tpu.parallel.collective import shard_map

logger = logging.getLogger("bigdl_tpu.optim")

__all__ = ["ShardedWeightUpdate", "wire_bytes_probe", "tuned_bucket_mb",
           "DEFAULT_BUCKET_MB"]

EF_KEY = "ef_residual"

DEFAULT_BUCKET_MB = 4.0


def tuned_bucket_mb(n_params: int, n_shards: int) -> float:
    """Gradient-bucket size for this (parameter count, shard count):
    the autotuned record when one exists (``tune`` over
    ``bucket_mb_candidates``, bigdl_tpu/tuning), the measured 4 MB
    default otherwise. Small buckets overlap more of the backward; big
    buckets amortize collective latency — the sweet spot moves with
    model depth and mesh size, which is why it is a tuning-record knob
    rather than a constant."""
    from bigdl_tpu.tuning.records import default_records
    cfg = default_records().lookup(
        "sharded_update", {"params": n_params, "shards": n_shards})
    if cfg:
        try:
            mb = float(cfg["bucket_mb"])
        except (KeyError, TypeError, ValueError):
            mb = 0.0
        if mb > 0:
            logger.info("sharded update: tuned bucket_mb=%.1f for "
                        "%d params on %d shards", mb, n_params, n_shards)
            return mb
        logger.warning("ignoring illegal sharded_update tuning record "
                       "%s", cfg)
    return DEFAULT_BUCKET_MB


class ShardedWeightUpdate:
    """Mechanics of the sharded update for one (mesh, optimizer, params)
    triple: bucket layout, state import/export, and the two step
    constructions. ``DistriOptimizer`` owns the training loop; this
    class owns the layout algebra."""

    def __init__(self, mesh, optim, params, *, axis: str = "data",
                 wire_codec=None, bucket_mb: float | None = None):
        self.mesh = mesh
        self.axis = axis
        self.n = int(mesh.shape[axis])
        self.optim = optim
        self.codec = get_codec(wire_codec)
        if bucket_mb is None:
            n_params = sum(int(l.size) for l in jax.tree.leaves(params))
            bucket_mb = tuned_bucket_mb(n_params, self.n)
        self.bucket_mb = float(bucket_mb)
        self.buckets = GradientBuckets(
            params, bucket_bytes=int(bucket_mb * (1 << 20)),
            n_shards=self.n)
        self.repl = NamedSharding(mesh, P())
        self.vec_shard = NamedSharding(mesh, P(axis))
        self.ef_shard = NamedSharding(mesh, P(axis, None))
        self._gather_jit = None
        self._export_jit = None

    # ------------------------------------------------------------------
    # spec/sharding trees
    # ------------------------------------------------------------------
    def _state_spec(self, st: dict) -> dict:
        out = {}
        for k, v in st.items():
            if k == EF_KEY:
                out[k] = self.buckets.spec(P(self.axis, None))
            elif isinstance(v, dict):
                out[k] = self.buckets.spec(P(self.axis))
            else:
                out[k] = P()
        return out

    def opt_state_sharding(self, st: dict) -> dict:
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), self._state_spec(st),
            is_leaf=lambda s: isinstance(s, P))

    def params_sharding(self):
        """jit in/out sharding for the step's params argument."""
        if self.codec is None:
            return self.repl
        return {k: self.vec_shard for k in self.buckets.keys}

    # ------------------------------------------------------------------
    # state import/export (checkpoint seam)
    # ------------------------------------------------------------------
    def import_params(self, params):
        """Initial/resumed params tree -> the step's params state:
        the replicated tree (implicit) or f32 master slices
        (explicit)."""
        if self.codec is None:
            return jax.device_put(params, self.repl)
        flat = self.buckets.flatten(params)
        return {k: jax.device_put(v, self.vec_shard)
                for k, v in flat.items()}

    def import_opt_state(self, tree_state: dict, params) -> dict:
        """Params-shaped optimizer state (fresh ``init_state`` or a
        checkpoint — replicated and ZeRO-1 layouts included) ->
        flat-bucket sharded state. The error-feedback residual is
        adopted when its bucket layout matches, reset to zeros (with a
        warning) otherwise."""
        pstruct = jax.tree.structure(params)
        out = {}
        saved_ef = None
        for k, v in tree_state.items():
            if k == EF_KEY:
                saved_ef = v
            elif isinstance(v, dict) and jax.tree.structure(v) == pstruct:
                out[k] = {bk: jax.device_put(vec, self.vec_shard)
                          for bk, vec in self.buckets.flatten(v).items()}
            else:
                out[k] = jax.device_put(v, self.repl)
        if self.codec is not None and self.codec.error_feedback:
            want = {bk: (self.n, s)
                    for bk, s in self.buckets.padded_sizes.items()}
            ok = (isinstance(saved_ef, dict)
                  and set(saved_ef) == set(want)
                  and all(tuple(saved_ef[bk].shape) == want[bk]
                          for bk in want))
            if ok:
                out[EF_KEY] = {bk: jax.device_put(jnp.asarray(saved_ef[bk]),
                                                  self.ef_shard)
                               for bk in want}
            else:
                if saved_ef is not None:
                    logger.warning(
                        "sharded update: checkpointed error-feedback "
                        "residual does not match the current bucket/mesh "
                        "layout — resetting to zeros")
                out[EF_KEY] = {
                    bk: jax.device_put(jnp.zeros(shape, jnp.float32),
                                       self.ef_shard)
                    for bk, shape in want.items()}
        elif saved_ef is not None:
            logger.info("sharded update: dropping checkpointed "
                        "error-feedback residual (codec carries none)")
        return out

    def gather_params(self, params_state):
        """Step params state -> full replicated f32 tree (for eval,
        ``model.sync`` and checkpoints — the canonical weights are the
        f32 masters, never the wire-rounded copies)."""
        if self.codec is None:
            return params_state
        if self._gather_jit is None:
            def gather(masters):
                full = {k: jax.lax.with_sharding_constraint(v, self.repl)
                        for k, v in masters.items()}
                return self.buckets.unflatten(full)
            self._gather_jit = jax.jit(gather)
        return self._gather_jit(params_state)

    def export_opt_state(self, st: dict) -> dict:
        """Flat-bucket sharded state -> params-shaped (ZeRO-1-compatible)
        trees; scalars pass through; the error-feedback residual stays
        in bucket form (layout-bound by nature)."""
        if self._export_jit is None:
            def export(st):
                out = {}
                for k, v in st.items():
                    if k == EF_KEY or not isinstance(v, dict):
                        out[k] = v
                    else:
                        out[k] = self.buckets.unflatten({
                            bk: jax.lax.with_sharding_constraint(vec,
                                                                 self.repl)
                            for bk, vec in v.items()})
                return out
            self._export_jit = jax.jit(export)
        return self._export_jit(st)

    # ------------------------------------------------------------------
    # implicit construction (bit-identical path)
    # ------------------------------------------------------------------
    def apply_update(self, grads, params, opt_state: dict):
        """Replicated gradient/params trees + flat sharded optimizer
        state -> (new replicated params tree, new sharded state).

        The flatten groups each bucket's leaves into one padded wire
        vector whose only consumer is sharded — XLA reduce-scatters the
        backward's gradient reduction into it where profitable — and the
        optimizer update runs under ``shard_map``, so every momentum/
        variance element is touched by exactly one replica. The final
        replication constraint is the parameter all-gather."""
        fg = self.buckets.flatten(grads)
        fp = self.buckets.flatten(params)
        bspec = self.buckets.spec(P(self.axis))
        sspec = self._state_spec(opt_state)

        def body(fg, fp, st):
            return self.optim.update(fg, fp, st)

        nfp, nst = shard_map(
            body, mesh=self.mesh, in_specs=(bspec, bspec, sspec),
            out_specs=(bspec, sspec), check_rep=False)(fg, fp, opt_state)
        full = {k: jax.lax.with_sharding_constraint(v, self.repl)
                for k, v in nfp.items()}
        return self.buckets.unflatten(full), nst

    # ------------------------------------------------------------------
    # explicit construction (compressed collectives)
    # ------------------------------------------------------------------
    def _gather_weights(self, master):
        """Inside shard_map: local master slice -> full flat bucket,
        wire-compressed (nearest rounding — weights carry no error
        feedback; every shard decodes the SAME bytes, so all replicas
        compute on identical weights and cannot drift)."""
        if self.codec.name == "fp32":
            return jax.lax.all_gather(master, self.axis, tiled=True)
        enc = self.codec.encode(master.reshape(1, -1))
        got = {k: jax.lax.all_gather(p, self.axis, tiled=True)
               for k, p in enc.items()}
        return self.codec.decode(got).reshape(-1)

    def _reduce_bucket(self, x, key):
        """Inside shard_map: my full-length f32 bucket contribution ->
        (my owned mean slice, my quantization residual or None). The
        wire is an ``all_to_all`` at codec width with per-destination-
        slice scales; accumulation happens AFTER decode, in f32."""
        rows = x.reshape(self.n, -1)
        if self.codec.name == "fp32":
            got = jax.lax.all_to_all(rows, self.axis, split_axis=0,
                                     concat_axis=0, tiled=False)
            return jnp.mean(got, axis=0), None
        enc = self.codec.encode(rows, key if self.codec.stochastic
                                else None)
        got = {}
        for k, p in enc.items():
            p2 = p if p.ndim > 1 else p[:, None]
            r = jax.lax.all_to_all(p2, self.axis, split_axis=0,
                                   concat_axis=0, tiled=False)
            got[k] = r if p.ndim > 1 else r[..., 0]
        out = jnp.sum(self.codec.decode(got), axis=0) / self.n
        residual = None
        if self.codec.error_feedback:
            residual = x - self.codec.decode(enc).reshape(-1)
        return out, residual

    def make_explicit_step(self, value_and_grad_fn, *, grad_clip=None,
                           num_microbatches: int = 1):
        """Build the explicit per-shard train step.

        ``value_and_grad_fn(params_tree, mstate, data, labels, key) ->
        ((loss, new_mstate), grads)`` runs on the LOCAL batch shard with
        a per-shard PRNG key. Returns ``step(masters, mstate, opt_state,
        rng, data, labels, epoch) -> (new_masters, new_mstate,
        new_opt_state, loss)`` ready for ``jax.jit``.

        ``num_microbatches`` > 1 scans the local shard through fwd/bwd
        in k strided microbatches with gradients accumulated in the
        scan carry (optim/accumulation.py); the weight all-gather, the
        bucketed compressed reduce-scatter (+ error feedback) and the
        sharded update all fire ONCE per accumulated step — k times
        fewer collective bytes per example."""
        ax, n = self.axis, self.n
        bkeys = list(self.buckets.keys)
        bspec = self.buckets.spec(P(ax))
        k = int(num_microbatches)

        def body(masters, mstate, st, key, data, labels, epoch):
            key = jax.random.fold_in(key, jax.lax.axis_index(ax))
            full = {bk: self._gather_weights(masters[bk]) for bk in bkeys}
            p_tree = self.buckets.unflatten(full)
            if k == 1:
                (loss, new_mstate), grads = value_and_grad_fn(
                    p_tree, mstate, data, labels, key)
            else:
                from bigdl_tpu.optim.accumulation import \
                    split_microbatches
                ds = split_microbatches(data, k)
                ls = split_microbatches(labels, k)
                # microbatch key stream branched away from the bucket
                # folds fold_in(key, 1+i) below — no key reuse across
                # dropout draws and stochastic-rounding draws
                mb_base = jax.random.fold_in(key, 0x6d62)

                def mb(carry, xs):
                    j, d, l = xs
                    (lv, ms), g = value_and_grad_fn(
                        p_tree, mstate, d, l,
                        jax.random.fold_in(mb_base, j))
                    gacc, lacc, msacc = carry
                    gacc = jax.tree.map(jnp.add, gacc, g)
                    msacc = jax.tree.map(
                        lambda a, c: a + c / k
                        if jnp.issubdtype(c.dtype, jnp.inexact) else c,
                        msacc, ms)
                    return (gacc, lacc + lv, msacc), None

                out_s = jax.eval_shape(
                    lambda p, d, l, kk: value_and_grad_fn(
                        p, mstate, d, l, kk),
                    p_tree, ds[0], ls[0], mb_base)
                (loss_s, ms_s), g_s = out_s
                zeros = lambda t: jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype), t)
                (grads, lsum, new_mstate), _ = jax.lax.scan(
                    mb, (zeros(g_s), zeros(loss_s), zeros(ms_s)),
                    (jnp.arange(k, dtype=jnp.int32), ds, ls))
                # per-microbatch losses/grads are local means over
                # equal-sized microbatches: one division restores the
                # local-batch mean exactly
                grads = jax.tree.map(lambda g: g / k, grads)
                loss = lsum / k
            loss = jax.lax.pmean(loss, ax)
            # per-shard batch statistics (the reference's per-core
            # semantics) merged across replicas; integer counters are
            # identical per shard and pass through
            new_mstate = jax.tree.map(
                lambda a: (jax.lax.pmean(a, ax)
                           if jnp.issubdtype(a.dtype, jnp.inexact) else a),
                new_mstate)
            fg = self.buckets.flatten(grads)
            st = dict(st, epoch=epoch)
            ef = st.pop(EF_KEY, None)
            gs, nef = {}, {}
            for i, bk in enumerate(bkeys):
                x = fg[bk]
                if ef is not None:
                    x = x + ef[bk].reshape(-1)
                slc, residual = self._reduce_bucket(
                    x, jax.random.fold_in(key, 1 + i))
                gs[bk] = slc
                if residual is not None:
                    nef[bk] = residual[None, :]
            gs = _clip_sharded(gs, grad_clip, ax)
            new_masters, nst = self.optim.update(gs, masters, st)
            if ef is not None:
                nst[EF_KEY] = nef
            return new_masters, new_mstate, nst, loss

        def step(masters, mstate, opt_state, rng, data, labels, epoch):
            sspec = self._state_spec(opt_state)
            return shard_map(
                body, mesh=self.mesh,
                in_specs=(bspec, P(), sspec, P(), P(ax), P(ax), P()),
                out_specs=(bspec, P(), sspec, P()),
                check_rep=False)(masters, mstate, opt_state, rng, data,
                                 labels, epoch)

        return step


def _clip_sharded(gs: dict, clip, axis: str) -> dict:
    """Gradient clipping on the sharded flat domain: the global L2 norm
    is a ``psum`` of per-slice square sums (equal to the replicated
    path's norm over the whole tree)."""
    if not clip:
        return gs
    if clip["min_value"] is not None:
        gs = {k: jnp.clip(v, clip["min_value"], clip["max_value"])
              for k, v in gs.items()}
    if clip["l2_norm"] is not None:
        local = sum(jnp.sum(jnp.square(v)) for v in gs.values())
        norm = jnp.sqrt(jax.lax.psum(local, axis))
        scale = jnp.minimum(1.0, clip["l2_norm"] / (norm + 1e-12))
        gs = {k: v * scale for k, v in gs.items()}
    return gs


def wire_bytes_probe(*, d_in: int = 256, d_hidden: int = 1024,
                     layers: int = 3, batch: int = 512,
                     bucket_kb: int = 512,
                     codecs=("fp32", "bf16", "int8"), mesh=None) -> dict:
    """Static per-step collective wire accounting for the explicit
    sharded step at each codec — lowering only, no execution, so it runs
    on any backend with a multi-device mesh (bench.py runs it on the
    8-virtual-CPU-device mesh; tests call it in-process).

    Returns ``{"wire_bytes_per_chip": {codec: bytes}, "ops": {...},
    "reduction_vs_fp32": {...}, "geometry": ..., "n_shards": N}``."""
    import numpy as np

    from bigdl_tpu.optim.sgd import SGD
    from bigdl_tpu.parallel.collective_bench import collective_bytes
    from bigdl_tpu.parallel.engine import get_mesh, data_sharding, \
        replicated

    mesh = mesh or get_mesh()
    n = int(mesh.shape["data"])
    rs = np.random.RandomState(0)
    dims = [d_in] + [d_hidden] * layers + [d_in]
    params = {f"l{i}": {"weight": rs.randn(dims[i + 1], dims[i])
                        .astype(np.float32) * 0.02,
                        "bias": np.zeros(dims[i + 1], np.float32)}
              for i in range(len(dims) - 1)}
    n_params = sum(l.size for l in jax.tree.leaves(params))

    def vag(p, mstate, data, labels, key):
        def loss_fn(pp):
            x = data
            for i in range(len(dims) - 1):
                x = x @ pp[f"l{i}"]["weight"].T + pp[f"l{i}"]["bias"]
                if i < len(dims) - 2:
                    x = jnp.tanh(x)
            return jnp.mean((x - labels) ** 2), mstate

        return jax.value_and_grad(loss_fn, has_aux=True)(p)

    data = rs.rand(batch, d_in).astype(np.float32)
    labels = rs.rand(batch, d_in).astype(np.float32)
    batch_shard = data_sharding(mesh)
    repl = replicated(mesh)
    out_bytes, out_ops = {}, {}
    for name in codecs:
        optim = SGD(learning_rate=0.1, momentum=0.9)
        su = ShardedWeightUpdate(mesh, optim, params, wire_codec=name,
                                 bucket_mb=bucket_kb / 1024.0)
        masters = su.import_params(params)
        opt0 = su.import_opt_state(optim.init_state(params), params)
        step = su.make_explicit_step(vag)
        jit_step = jax.jit(step)
        compiled = jit_step.lower(
            masters, {}, opt0, jax.random.PRNGKey(0),
            jax.device_put(jnp.asarray(data), batch_shard),
            jax.device_put(jnp.asarray(labels), batch_shard),
            jax.device_put(jnp.ones((), jnp.int32), repl)).compile()
        acct = collective_bytes(compiled.as_text(), n)
        out_bytes[name] = acct["wire_bytes_per_chip"]
        out_ops[name] = acct["ops"]
    base = out_bytes.get("fp32")
    reduction = {k: (base / v if base and v else None)
                 for k, v in out_bytes.items()}
    return {"wire_bytes_per_chip": out_bytes, "ops": out_ops,
            "reduction_vs_fp32": reduction,
            "geometry": f"mlp d{d_in}x{d_hidden} L{layers} B{batch} "
                        f"({n_params} params, bucket {bucket_kb} KB)",
            "n_params": n_params, "n_shards": n}

"""Triggers — predicates over the training state.

Reference parity: optim/Trigger.scala:21-70 — ``everyEpoch``,
``severalIteration(n)``, ``maxEpoch(n)``, ``maxIteration(n)``.
State keys follow the reference's state Table: ``neval`` (iteration count),
``epoch``, plus ``is_epoch_end`` maintained by the optimizers.

``requires`` declares which DEVICE-produced state keys a trigger reads
(``min_loss`` -> ``{"loss"}``); combinators union their children's sets.
The async-dispatch train loops consult it (docs/PERFORMANCE.md): a
trigger that reads ``loss`` forces a readback every iteration so the
stopping decision sees the true per-step value, while the default
``max_epoch``/``max_iteration`` paths — pure host counters — let the
loop dispatch ahead without ever syncing.
"""
from __future__ import annotations

__all__ = ["Trigger", "every_epoch", "several_iteration", "max_epoch",
           "max_iteration", "min_loss", "or_trigger", "and_trigger"]


class Trigger:
    def __init__(self, fn, desc="", requires=frozenset()):
        self._fn = fn
        self._desc = desc
        #: device-produced state keys the predicate reads (e.g. "loss")
        self.requires = frozenset(requires)

    def __call__(self, state) -> bool:
        return bool(self._fn(state))

    def __repr__(self):
        return f"Trigger({self._desc})"


def every_epoch() -> Trigger:
    """Fires at each epoch boundary (reference Trigger.everyEpoch —
    implemented there with a cached epoch counter; here the optimizers set
    ``is_epoch_end``)."""
    return Trigger(lambda s: s.get("is_epoch_end", False), "everyEpoch")


def several_iteration(interval: int) -> Trigger:
    """(reference Trigger.severalIteration)"""
    return Trigger(lambda s: s["neval"] % interval == 0,
                   f"severalIteration({interval})")


def max_epoch(n: int) -> Trigger:
    """(reference Trigger.maxEpoch)"""
    return Trigger(lambda s: s["epoch"] > n, f"maxEpoch({n})")


def max_iteration(n: int) -> Trigger:
    """(reference Trigger.maxIteration)"""
    return Trigger(lambda s: s["neval"] > n, f"maxIteration({n})")


def min_loss(value: float) -> Trigger:
    return Trigger(lambda s: s.get("loss", float("inf")) < value,
                   f"minLoss({value})", requires={"loss"})


def _combined(op, name, triggers):
    desc = f"{name}({', '.join(t._desc for t in triggers)})"
    requires = frozenset().union(
        *(getattr(t, "requires", frozenset()) for t in triggers))
    return Trigger(lambda s: op(t(s) for t in triggers), desc,
                   requires=requires)


def or_trigger(*triggers: Trigger) -> Trigger:
    return _combined(any, "or", triggers)


def and_trigger(*triggers: Trigger) -> Trigger:
    return _combined(all, "and", triggers)

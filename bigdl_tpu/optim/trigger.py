"""Triggers — predicates over the training state.

Reference parity: optim/Trigger.scala:21-70 — ``everyEpoch``,
``severalIteration(n)``, ``maxEpoch(n)``, ``maxIteration(n)``.
State keys follow the reference's state Table: ``neval`` (iteration count),
``epoch``, plus ``is_epoch_end`` maintained by the optimizers.
"""
from __future__ import annotations

__all__ = ["Trigger", "every_epoch", "several_iteration", "max_epoch",
           "max_iteration", "min_loss", "or_trigger", "and_trigger"]


class Trigger:
    def __init__(self, fn, desc=""):
        self._fn = fn
        self._desc = desc

    def __call__(self, state) -> bool:
        return bool(self._fn(state))

    def __repr__(self):
        return f"Trigger({self._desc})"


def every_epoch() -> Trigger:
    """Fires at each epoch boundary (reference Trigger.everyEpoch —
    implemented there with a cached epoch counter; here the optimizers set
    ``is_epoch_end``)."""
    return Trigger(lambda s: s.get("is_epoch_end", False), "everyEpoch")


def several_iteration(interval: int) -> Trigger:
    """(reference Trigger.severalIteration)"""
    return Trigger(lambda s: s["neval"] % interval == 0,
                   f"severalIteration({interval})")


def max_epoch(n: int) -> Trigger:
    """(reference Trigger.maxEpoch)"""
    return Trigger(lambda s: s["epoch"] > n, f"maxEpoch({n})")


def max_iteration(n: int) -> Trigger:
    """(reference Trigger.maxIteration)"""
    return Trigger(lambda s: s["neval"] > n, f"maxIteration({n})")


def min_loss(value: float) -> Trigger:
    return Trigger(lambda s: s.get("loss", float("inf")) < value,
                   f"minLoss({value})")


def or_trigger(*triggers: Trigger) -> Trigger:
    return Trigger(lambda s: any(t(s) for t in triggers), "or")


def and_trigger(*triggers: Trigger) -> Trigger:
    return Trigger(lambda s: all(t(s) for t in triggers), "and")

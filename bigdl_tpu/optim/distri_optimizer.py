"""DistriOptimizer — synchronous data-parallel training over a device mesh.

Reference parity: optim/DistriOptimizer.scala:34-573, the heart of the
reference (call stack SURVEY §3.1). Its per-iteration machinery:

  getWeights (all-gather FP16 slices) → per-core fwd/bwd → chunked gradient
  merge → putGradients (reduce-scatter slices through BlockManager) →
  per-slice SGD → sendWeightPartition

collapses into ONE pjit-compiled step: the batch is sharded along the
``data`` mesh axis, parameters are replicated, and XLA inserts the gradient
all-reduce over ICI during the backward pass — the BlockManager
reduce-scatter/all-gather pair (parameters/AllReduceParameter.scala:53-229)
becomes a single fused collective with no host round-trips. Per-slice
optimizer-state ownership (the reference keeps SGD state only for the local
partition, DistriOptimizer.scala:231-232) maps to optional optimizer-state
sharding along the same axis (``shard_optim_state=True``, cf. "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training").

Straggler dropping (invokeAndWait2 timeouts, :153-176) has no SPMD
equivalent — lockstep collectives can't drop members — so per-phase Metrics
are kept instead (SURVEY §7 translation table).

BatchNorm note: under global-array semantics batch statistics are computed
over the GLOBAL batch (XLA inserts the cross-device mean); the reference's
stats were per-core-replica. Documented difference, generally an accuracy
improvement.
"""
from __future__ import annotations

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.observability import trace
from bigdl_tpu.optim.optimizer import Optimizer
from bigdl_tpu.parallel.engine import (get_mesh, data_sharding, replicated)

logger = logging.getLogger("bigdl_tpu.optim")

__all__ = ["DistriOptimizer"]


class DistriOptimizer(Optimizer):
    """(reference optim/DistriOptimizer.scala)"""

    def __init__(self, model, dataset, criterion, batch_size=None, *,
                 mesh=None, shard_optim_state: bool = False,
                 shard_weight_update: bool = False, wire_codec=None,
                 bucket_mb: float | None = None,
                 tensor_parallel: bool | str = False,
                 sequence_parallel: bool | str = False, **kw):
        super().__init__(model, dataset, criterion, batch_size, **kw)
        self.mesh = mesh
        self.shard_optim_state = shard_optim_state
        # fully cross-replica-sharded update (optim/sharded_update.py):
        # reduce-scatter grads in buckets, 1/N update math + optimizer
        # state per replica, all-gather params; wire_codec None keeps
        # the bit-identical implicit construction, "fp32"/"bf16"/"int8"
        # run explicit (compressed) per-shard collectives
        # bucket_mb None = resolve at run time: the autotuned record for
        # this (param count, data-axis size), else the 4 MB default
        # (optim/sharded_update.py tuned_bucket_mb)
        if shard_weight_update or wire_codec is not None:
            self.set_sharded_update(True, wire_codec=wire_codec,
                                    bucket_mb=bucket_mb)
        elif bucket_mb is not None:
            self.bucket_mb = float(bucket_mb)
        # True / axis name: store params sharded over the mesh 'model'
        # axis and let XLA's SPMD partitioner split the math
        # (parallel/tensor_parallel.py)
        self.tensor_parallel = tensor_parallel
        # True / axis name: shard the batch's SEQUENCE dim (dim 1) over
        # the mesh 'seq' axis as well — pair with a model whose attention
        # runs ring/Ulysses over that axis (models/transformer/model.py
        # sequence_parallel=...). Composes with data and tensor
        # parallelism: one jitted step over a dp x tp x sp mesh.
        self.sequence_parallel = sequence_parallel
        # cached after _account_collectives — the hot loop must not
        # re-read the metrics dict every iteration
        self._wire_bytes = 0.0

    def _account_collectives(self, compiled, n_devices: int) -> None:
        """Static per-step collective-bytes accounting from the compiled
        HLO — the XLA-era equivalent of the reference's put/get-gradient
        phase instrumentation (AllReduceParameter.scala:134-228). Runs
        once per compile; read back via ``metrics.summary()``."""
        from bigdl_tpu.parallel.collective_bench import collective_bytes
        try:
            acct = collective_bytes(compiled.as_text(), n_devices)
        except Exception as e:   # accounting must never break training
            logger.debug(f"collective accounting unavailable: {e}")
            return
        self.metrics.set("collective ops per step", acct["ops"])
        self.metrics.set("collective logical bytes per step",
                         acct["logical_bytes"])
        self.metrics.set("collective wire bytes per chip per step",
                         acct["wire_bytes_per_chip"])
        self._wire_bytes = float(acct["wire_bytes_per_chip"])
        logger.info(
            "collectives per step: %d ops, %.1f MB logical, %.1f MB wire "
            "per chip (ring estimate)", acct["ops"],
            acct["logical_bytes"] / 1e6, acct["wire_bytes_per_chip"] / 1e6)

    def _init_pipeline(self, mesh):
        """Validate + build the PipelineParallel mechanics (None when
        pipeline_stages == 1). The pipeline path owns its own layouts,
        so the features that assume a replicated or data-only layout
        are refused loudly here."""
        if self.pipeline_stages <= 1:
            return None
        if self.tensor_parallel or self.sequence_parallel:
            raise ValueError(
                "pipeline_stages shards the layer stack over the "
                "'pipe' axis and does not compose with "
                "tensor_parallel/sequence_parallel yet — pick one "
                "model-sharding scheme")
        if self.shard_optim_state:
            raise ValueError(
                "pipeline_stages subsumes shard_optim_state: optimizer "
                "state is already stored per stage (and 1/N over the "
                "data axis under shard_weight_update) — drop "
                "shard_optim_state")
        if self.wire_codec is not None:
            raise ValueError(
                "pipeline_stages composes with the implicit sharded "
                "update only (wire_codec=None) — the explicit "
                "compressed-wire step is a whole-step shard_map that "
                "cannot nest the pipeline schedule")
        if self._pad_stage is not None:
            raise ValueError(
                "pipeline_stages does not compose with "
                "pad_partial_batches — pad in the dataset pipeline")
        if self.expert_parallel:
            raise ValueError(
                "pipeline_stages + expert_parallel in one stack is not "
                "supported yet: MoE layers carry per-step state the "
                "pipeline's stateless-block contract refuses")
        from bigdl_tpu.parallel.pipeline import PipelineParallel
        pp = PipelineParallel(
            mesh, self.model, self.criterion, self.optim_method,
            n_stages=self.pipeline_stages,
            num_microbatches=self.grad_accumulation,
            schedule=self.pipeline_schedule,
            virtual_stages=self.pipeline_virtual_stages,
            data_axis="data", remat_policy=self.remat_policy,
            sharded_update=(self.shard_weight_update
                            or self.wire_codec is not None),
            bucket_mb=self.bucket_mb)
        from bigdl_tpu.parallel.pipeline import pipeline_schedule_stats
        st = pipeline_schedule_stats(
            pp.m, pp.s, pp.schedule, virtual_stages=pp.v)
        logger.info(
            "pipeline: %d stages x %d virtual, %s schedule, M=%d "
            "microbatches — modeled bubble %.3f, stash %d microbatches",
            pp.s, pp.v, pp.schedule, pp.m, st["bubble_fraction"],
            st["peak_stash_microbatches"])
        return pp

    def _publish_expert_telemetry(self, mstate) -> None:
        """Epoch-boundary MoE telemetry publish: ONE batched
        ``jax.device_get`` over every MoE layer's state leaves — the
        loop never pays a per-step sync for it."""
        if not self.expert_parallel:
            return
        from bigdl_tpu.parallel.expert import publish_moe_metrics
        try:
            stats = publish_moe_metrics(mstate)
        except Exception as e:    # telemetry must never break training
            logger.debug("moe telemetry publish failed: %s", e)
            return
        if stats and logger.isEnabledFor(logging.INFO):
            for layer, vals in stats.items():
                logger.info(
                    "moe[%s]: dropped ranks %.1f%%, tokens %.1f%%, "
                    "overflow %.0f, imbalance %.2f", layer,
                    100 * vals.get("moe_dropped_rank_frac", 0.0),
                    100 * vals.get("moe_dropped_token_frac", 0.0),
                    vals.get("moe_overflow_tokens", 0.0),
                    vals.get("moe_load_imbalance", 0.0))

    def _init_sharded_update(self, mesh, params):
        """Validate + build the ShardedWeightUpdate mechanics (None when
        the feature is off). Raises on configurations whose layouts
        conflict with the flat-bucket construction."""
        if not (self.shard_weight_update or self.wire_codec is not None):
            return None
        if self.tensor_parallel or self.sequence_parallel:
            raise ValueError(
                "shard_weight_update shards flat parameter buckets over "
                "the data axis and requires replicated parameters — it "
                "does not compose with tensor_parallel/sequence_parallel")
        if self.shard_optim_state:
            raise ValueError(
                "shard_weight_update subsumes shard_optim_state (ZeRO-1): "
                "optimizer state is already stored 1/N per replica in "
                "bucket slices — drop shard_optim_state")
        if "data" not in mesh.axis_names:
            raise ValueError(
                f"shard_weight_update needs a 'data' mesh axis, mesh has "
                f"{mesh.axis_names}")
        from bigdl_tpu.parameters.compression import get_codec
        codec = get_codec(self.wire_codec)
        if codec is not None and self._pad_stage is not None:
            raise ValueError(
                "pad_partial_batches does not compose with an explicit "
                "wire codec: the per-shard loss cannot see the global "
                "valid-row count — use wire_codec=None (implicit sharded "
                "update) or disable padding")
        optim = self.optim_method
        for what in ("learning_rates", "weight_decays"):
            spec = getattr(optim, what, None)
            if spec is not None and jax.tree.structure(spec) != \
                    jax.tree.structure(0):
                raise ValueError(
                    f"shard_weight_update flattens params into wire "
                    f"buckets, so a params-shaped {what} tree cannot be "
                    "matched leafwise — use scalar hyperparameters")
        from bigdl_tpu.optim.sharded_update import ShardedWeightUpdate
        su = ShardedWeightUpdate(mesh, optim, params, wire_codec=codec,
                                 bucket_mb=self.bucket_mb)
        logger.info(
            "sharded weight update: %d buckets over %d-way data axis, "
            "wire codec %s", len(su.buckets), su.n,
            codec.name if codec is not None else "implicit/fp32")
        return su

    def _shard_batch(self, data, labels, sharding,
                     label_sharding=None):
        """Lay a host batch out across the data axis.

        Multi-host: each process passes its local shard and the global
        array is assembled over ICI/DCN
        (``jax.make_array_from_process_local_data`` — the TPU equivalent of
        the reference's locality-zipped RDD partitions,
        ZippedPartitionsWithLocalityRDD.scala:27-118).
        """
        if label_sharding is None:
            # sequence-parallel: labels shard like data when they carry a
            # sequence dim, over 'data' alone when rank-1
            from jax.sharding import NamedSharding, PartitionSpec as P
            label_sharding = (sharding if np.ndim(labels) >= 2
                              else NamedSharding(sharding.mesh, P("data")))
        if jax.process_count() > 1:
            data = jax.make_array_from_process_local_data(sharding, data)
            labels = jax.make_array_from_process_local_data(label_sharding,
                                                            labels)
            return data, labels
        return (jax.device_put(data, sharding),
                jax.device_put(labels, label_sharding))

    def _emit_step(self, e: dict, loss: float) -> None:
        super()._emit_step(e, loss)
        if self._wire_bytes > 0 and not e["compiled"]:
            # device step time >= collective time, so this is a LOWER
            # bound on link bandwidth — the honest in-training readout
            # (the isolated figure comes from parallel/collective_bench);
            # compile iterations are excluded, their wall time is
            # compilation, not the link. Under async dispatch the device
            # time is window-amortized (docs/PERFORMANCE.md), so this
            # stays a per-window average rather than a per-step sample.
            self.metrics.record(
                "allreduce GB/s (wire bytes / device step, lower bound)",
                self._wire_bytes / max(e["device_time"], 1e-9) / 1e9)
        if logger.isEnabledFor(logging.DEBUG):
            logger.debug(self.metrics.summary())

    def _optimize_impl(self):
        model, criterion, optim = self.model, self.criterion, \
            self.optim_method
        mesh = self.mesh or get_mesh()
        self._ckpt_mesh = mesh   # recorded in checkpoint manifests
        n_shards = int(np.prod(mesh.devices.shape))
        if self.tensor_parallel or self.shard_optim_state:
            # params/optimizer-state leaves carry mesh shardings on these
            # paths: the concat-grouped small-leaf update miscompiles
            # under GSPMD (values summed over the data axis — see
            # SGD.group_small_leaves); force the per-leaf form
            optim.group_small_leaves = False
        model.materialize()
        model.training()
        params, mstate = model.params, model.state
        driver_state = {"epoch": int(self.state.get("epoch", 1)),
                        "neval": int(self.state.get("neval", 1)),
                        "is_epoch_end": False, "loss": float("inf")}
        if self.expert_parallel and \
                self.expert_parallel not in mesh.axis_names:
            raise ValueError(
                f"expert_parallel={self.expert_parallel!r} needs that "
                f"mesh axis — build the mesh with Engine.init(axes="
                f"{{'data': N, {self.expert_parallel!r}: E}}) (mesh "
                f"has {mesh.axis_names})")
        if self.expert_parallel and self.wire_codec is not None:
            raise ValueError(
                "expert_parallel does not compose with an explicit "
                "wire codec: the per-shard compressed step cannot nest "
                "the MoE dispatch's own shard_map — use "
                "wire_codec=None")
        opt_state, rng, count_this_epoch, batches_to_skip = \
            self._resume(optim, params)
        pp = self._init_pipeline(mesh)
        su = None if pp is not None \
            else self._init_sharded_update(mesh, params)
        if su is None and isinstance(opt_state, dict) \
                and "ef_residual" in opt_state:
            # resuming a compressed-collective checkpoint into a run
            # without error feedback: the residual is meaningless here
            opt_state = {k: v for k, v in opt_state.items()
                         if k != "ef_residual"}
            logger.info("dropping checkpointed error-feedback residual "
                        "(sharded update with int8 codec not active)")

        repl = replicated(mesh)
        # a pure-pipeline mesh (axes={'pipe': S}) has no data axis: the
        # batch replicates and every stage sees the full microbatches
        batch_shard = (repl if "data" not in mesh.axis_names
                       else data_sharding(mesh))
        label_shard = batch_shard
        sp_axis, sp_size = None, 1
        if self.sequence_parallel:
            from jax.sharding import NamedSharding, PartitionSpec as P
            sp_axis = (self.sequence_parallel
                       if isinstance(self.sequence_parallel, str)
                       else "seq")
            sp_size = int(mesh.shape[sp_axis])
            batch_shard = NamedSharding(mesh, P("data", sp_axis))
            # labels may be rank-1 (sequence classification) — their
            # placement is rank-derived per batch and the jitted step
            # inherits it (in_shardings=None for that arg)
            label_shard = None
        # the batch's dim 0 shards over the axes named in the spec's
        # first entry — a seq/model axis does not constrain batch size
        dim0 = batch_shard.spec[0] if batch_shard.spec else None
        if dim0 is None:
            batch_div = 1
        elif isinstance(dim0, (tuple, list)):
            batch_div = int(np.prod([mesh.shape[a] for a in dim0]))
        else:
            batch_div = int(mesh.shape[dim0])
        param_shard, opt_shard = repl, repl
        tp_tree = None
        if self.tensor_parallel:
            from bigdl_tpu.parallel.tensor_parallel import shard_params
            tp_axis = (self.tensor_parallel
                       if isinstance(self.tensor_parallel, str)
                       else "model")
            param_shard = tp_tree = shard_params(params, mesh, tp_axis)
        if self.shard_optim_state:
            # ZeRO-1 layout: each replica keeps 1/N of momentum/accums
            # (composes with TP — the TP layout wins where present)
            from bigdl_tpu.parallel.tensor_parallel import \
                shard_optim_state_zero1
            opt_shard = shard_optim_state_zero1(
                opt_state, params, mesh, param_shardings=tp_tree)
        elif tp_tree is not None:
            from bigdl_tpu.parallel.tensor_parallel import \
                sharding_for_tree_like
            opt_shard = sharding_for_tree_like(opt_state, params,
                                               tp_tree, repl)
        if pp is not None:
            # pipeline owns the layouts: device-major stacked layer
            # params over 'pipe', optimizer state in the matching
            # stacked (or per-stage bucket-slice) form
            # (parallel/pipeline.py)
            mstate = jax.device_put(mstate, repl)
            params = pp.import_params(params)
            opt_state = pp.import_opt_state(opt_state)
            param_shard = pp.params_sharding()
            opt_shard = pp.opt_state_sharding(opt_state)
        elif su is not None:
            # sharded update owns both layouts: flat bucket slices for
            # optimizer state, and (explicit codecs) master slices for
            # params (optim/sharded_update.py)
            mstate = jax.device_put(mstate, repl)
            opt_state = su.import_opt_state(opt_state, params)
            params = su.import_params(params)
            param_shard = su.params_sharding()
            opt_shard = su.opt_state_sharding(opt_state)
        else:
            # mesh-portable placement (elastic/redistribute.py): the
            # resumed host arrays land on THIS run's mesh whatever mesh
            # they were saved under — 8 devices -> 4 is a resize, not an
            # error (checkpoints hold host-global arrays, so this is
            # placement, never a data transform)
            from bigdl_tpu.elastic.redistribute import redistribute
            src_layout = self.state.get("mesh_layout")
            params = redistribute(params, src_layout, mesh,
                                  shardings=param_shard, what="params")
            mstate = redistribute(mstate, src_layout, mesh,
                                  shardings=repl, what="model state")
            opt_state = redistribute(opt_state, src_layout, mesh,
                                     shardings=opt_shard,
                                     what="optimizer state")

        use_mask = self._pad_stage is not None
        masked = None
        if use_mask:
            from bigdl_tpu.nn.criterion import MaskedCriterion
            masked = MaskedCriterion(criterion)

        # memory-for-throughput knobs applied at step construction:
        # named remat policy around the forward, microbatched gradient
        # accumulation around fwd/bwd (optim/remat.py,
        # optim/accumulation.py); "none" + k=1 is EXACTLY the plain step
        from bigdl_tpu.optim.remat import remat_forward
        fwd = remat_forward(model, self.remat_policy)

        if pp is not None:
            # combined forward/backward schedule in ONE compiled step:
            # remat applies per chunk inside the schedule's backward
            # recompute, the data-axis reduction (or the per-stage
            # bucketed reduce-scatter under shard_weight_update) and
            # the optimizer update fire once per accumulated step
            # (parallel/pipeline.py)
            train_step = pp.make_train_step(
                grad_clip=self.grad_clip,
                input_transform=self.input_transform)
        elif su is not None and su.codec is not None:
            # explicit construction: the whole step runs per-shard under
            # shard_map — local forward/backward (scanned k microbatches
            # at a time under grad accumulation, with the bucketed
            # compressed reduce-scatter + error feedback firing ONCE on
            # the accumulated grads), sharded update on f32 masters,
            # compressed param all-gather
            def local_vag(p, mstate_in, data, labels, key):
                if self.input_transform is not None:
                    data = self.input_transform(data)

                def loss_fn(pp):
                    y, new_mstate = fwd(pp, mstate_in, data,
                                        training=True, rng=key)
                    return criterion.apply(y, labels), new_mstate

                return jax.value_and_grad(loss_fn, has_aux=True)(p)

            explicit_step = su.make_explicit_step(
                local_vag, grad_clip=self.grad_clip,
                num_microbatches=self.grad_accumulation)

            def train_step(params, mstate, opt_state, rng, data, labels,
                           epoch, n_valid=None):
                return explicit_step(params, mstate, opt_state, rng,
                                     data, labels, epoch)
        else:
            # global-view construction: mean over the GLOBAL batch — the
            # gradient allreduce this induces in backward IS the
            # reference's whole parameters/AllReduceParameter machinery;
            # under the implicit sharded update the update math and
            # optimizer state run 1/N per replica (su.apply_update), and
            # with grad accumulation the induced reduction fires once
            # per ACCUMULATED step (k x fewer collective bytes per
            # example)
            from bigdl_tpu.optim.accumulation import make_train_step
            train_step = make_train_step(
                fwd=fwd, criterion=criterion, masked=masked,
                input_transform=self.input_transform,
                grad_clip=self.grad_clip,
                update_fn=(su.apply_update if su is not None
                           else optim.update),
                num_microbatches=self.grad_accumulation,
                aux_loss=self._aux_loss_fn())

        # label_shard is None under sequence_parallel (rank-derived at
        # placement, _shard_batch); jit then inherits the arg sharding
        in_shardings = (param_shard, repl, opt_shard, repl, batch_shard,
                        label_shard, None)
        if use_mask:
            in_shardings += (None,)   # n_valid: replicated scalar
        jit_step = jax.jit(
            train_step,
            donate_argnums=(0, 1, 2),
            in_shardings=in_shardings,
            out_shardings=(param_shard, repl, opt_shard, repl))
        # explicit lower -> compile -> cache pipeline
        # (tuning/aot_cache.py): one executable per batch shape (partial
        # final batches recompile, like jit would), loaded from the
        # persistent AOT cache on a warm restart instead of recompiling;
        # collective accounting reads the first executable's HLO
        from bigdl_tpu.tuning.aot_cache import StepCompiler
        step_pipeline = StepCompiler(
            jit_step, name="distri_train_step",
            cache=self._aot_cache() or False, mesh=mesh,
            donate_argnums=(0, 1, 2), extra=self._step_key_extra())

        def eval_apply(params, mstate, data):
            if self.input_transform is not None:
                data = self.input_transform(data)
            out, _ = model.apply(params, mstate, data, training=False)
            return out

        # sharded update / pipeline: evaluation/checkpoint see the
        # gathered params tree, so eval shardings are replicated
        eval_param_shard = (repl if su is not None or pp is not None
                            else param_shard)
        if jax.process_count() > 1:
            # multi-host in-training validation: per-process shards can't
            # be device_put onto the global mesh (round-5 review finding:
            # that raised before the cross-host reduce was ever reached).
            # Each process evaluates its own shard on its LOCAL devices
            # with the host-gathered params _validate provides, and
            # Optimizer._validate merges results across hosts.
            from bigdl_tpu.optim.validator import local_sharded_eval
            eval_fn = local_sharded_eval(eval_apply)
        else:
            from bigdl_tpu.optim.validator import _padded_eval
            jit_eval = jax.jit(eval_apply,
                               in_shardings=(eval_param_shard, repl,
                                             batch_shard),
                               out_shardings=batch_shard)
            # params stay in their training placement (param_shard may be
            # ZeRO-sharded) — only the batch is padded/placed/trimmed
            eval_fn = _padded_eval(jit_eval, batch_shard, n_shards)

        def place(batch):
            """Host batch -> mesh-sharded device batch, run on the
            prefetch worker (depth >= 1) so placement overlaps the
            in-flight device steps; also the depth-0 inline stage."""
            if isinstance(batch.data, jax.Array):
                # a user-pipeline DevicePrefetcher already placed it
                # (overlapped upstream) — don't round-trip it, but keep
                # the friendly divisibility error for sharding-less
                # prefetchers and user-placed arrays
                if batch.data.shape[0] % batch_div != 0:
                    raise ValueError(
                        f"global batch {batch.data.shape[0]} not "
                        f"divisible by the {batch_div} data-axis shards "
                        "(reference Utils.getBatchSize divisibility "
                        "requirement, dataset/Utils.scala:25-47)")
                return batch
            data = np.asarray(batch.data)
            labels = np.asarray(batch.labels)
            global_n = data.shape[0] * jax.process_count()
            if global_n % batch_div != 0:
                raise ValueError(
                    f"global batch {global_n} not divisible by the "
                    f"{batch_div} data-axis shards (reference "
                    "Utils.getBatchSize divisibility requirement, "
                    "dataset/Utils.scala:25-47)")
            if sp_size > 1 and data.shape[1] % sp_size != 0:
                raise ValueError(
                    f"sequence length {data.shape[1]} not divisible "
                    f"by the {sp_size}-way '{sp_axis}' mesh axis "
                    "(sequence_parallel shards batch dim 1)")
            data, labels = self._shard_batch(data, labels, batch_shard,
                                             label_shard)
            from bigdl_tpu.dataset.sample import MiniBatch
            return MiniBatch(data, labels, valid=batch.valid)

        epoch_start_host_rng = self._host_rng_snapshot()
        epoch_size = self.dataset.size()
        batches_this_epoch = batches_to_skip
        pipeline = self._open_train_pipeline(
            place, skip=batches_to_skip, consumed=count_this_epoch,
            records_scale=jax.process_count())
        window, lockstep = self._dispatch_window()
        pending: list[dict] = []
        wallclock_start = time.perf_counter()

        try:
            while self.end_when is None or not self.end_when(driver_state):
                driver_state["is_epoch_end"] = False
                self._profile_hook(driver_state["neval"])
                t0 = time.perf_counter()
                with trace.span("input wait"):
                    # queue pop at depth >= 1: the batch was assembled,
                    # checked, and mesh-placed on the worker thread
                    # ("input produce")
                    batch = next(pipeline)
                t1 = time.perf_counter()
                data_time = t1 - t0
                data, labels = batch.data, batch.labels
                if batch.valid is not None:
                    # padded batch: count the REAL rows (single
                    # controller — _init_pad_stage refuses multi-host)
                    global_n = int(batch.valid)
                else:
                    global_n = int(data.shape[0])
                rng, step_rng = jax.random.split(rng)
                epoch_arr = jnp.asarray(driver_state["epoch"], jnp.int32)
                step_args = (step_rng, data, labels, epoch_arr)
                if use_mask:
                    step_args += (jnp.asarray(global_n, jnp.int32),)
                shape_key = (data.shape, labels.shape)
                compiled_this_iter = shape_key not in step_pipeline
                # lower/compile (or AOT-cache load) on first sight of a
                # shape; compile counts, executable FLOPs and peak HBM
                # land in the registry either way
                # (observability/compile_watch.py)
                compiled, _ = step_pipeline.get(
                    shape_key, (params, mstate, opt_state) + step_args)
                if compiled_this_iter and len(step_pipeline) == 1:
                    self._account_collectives(compiled, n_shards)
                with trace.span("device step"):
                    # dispatch only — loss stays on device; the packed
                    # readback happens at drain time (docs/PERFORMANCE.md).
                    # Honest phase metrics: the reference's get-weights/
                    # compute/aggregate phases fuse inside the jitted
                    # step, so what's measurable is input wait vs device
                    # step (see metrics.py)
                    params, mstate, opt_state, loss = compiled(
                        params, mstate, opt_state, *step_args)
                t2 = time.perf_counter()
                self._telemetry_step()
                n = global_n  # records consumed across all hosts
                count_this_epoch += n
                batches_this_epoch += 1
                pending.append({"epoch": driver_state["epoch"],
                                "count": count_this_epoch,
                                "epoch_size": epoch_size,
                                "neval": driver_state["neval"],
                                "wallclock": time.perf_counter()
                                - wallclock_start,
                                "loss": loss, "n": n,
                                "step_time": t2 - t0,
                                "data_time": data_time,
                                "device_time": t2 - t1,
                                "compiled": compiled_this_iter})
                if len(pending) >= window:
                    self._drain_pending(pending, driver_state,
                                        lockstep or "window full")
                driver_state["neval"] += 1
                if count_this_epoch >= epoch_size:
                    self._drain_pending(pending, driver_state, "epoch end")
                    self._emit_input_wait_fraction(driver_state["neval"])
                    # epoch-end checkpoint barrier: pending async saves
                    # commit before the next epoch dispatches
                    self._ckpt_barrier()
                    driver_state["epoch"] += 1
                    driver_state["is_epoch_end"] = True
                    count_this_epoch = 0
                    batches_this_epoch = 0
                    # join the worker BEFORE shuffle() mutates the order
                    # it iterates (thread-safety contract,
                    # dataset/prefetch.py), then restart on the fresh
                    # epoch's iterator
                    pipeline.close()
                    self.dataset.shuffle()
                    epoch_start_host_rng = self._host_rng_snapshot()
                    pipeline = self._open_train_pipeline(
                        place, records_scale=jax.process_count())
                    # MoE dispatch telemetry -> registry, once per
                    # epoch (one batched readback, never per-step)
                    self._publish_expert_telemetry(mstate)
                fire_val, fire_ckpt = self._fires(driver_state)
                ptree, opt_export = params, opt_state
                if fire_val or fire_ckpt:
                    # validation/checkpoint read host-visible state: flush
                    # the window first, then publish params (host-side
                    # tree walk is overhead on deep models). Sharded
                    # update: gather the f32 masters and re-shape the
                    # bucketed optimizer state back to the params-shaped
                    # (ZeRO-1-compatible) checkpoint layout
                    self._drain_pending(pending, driver_state,
                                        "validation/checkpoint trigger")
                    if su is not None:
                        ptree = su.gather_params(params)
                        if fire_ckpt:
                            opt_export = su.export_opt_state(opt_state)
                    elif pp is not None:
                        ptree = pp.gather_params(params)
                        if fire_ckpt:
                            opt_export = pp.export_opt_state(opt_state)
                    model.sync(ptree, mstate)
                self._validate(eval_fn, ptree, mstate, driver_state,
                               fire=fire_val)
                self._checkpoint(driver_state, opt_export, rng,
                                 count_this_epoch, batches_this_epoch,
                                 epoch_start_host_rng, fire=fire_ckpt)
        finally:
            pipeline.close()

        self._drain_pending(pending, driver_state, "training end")
        # exit barrier: every handed-off checkpoint is committed (and any
        # background save error raised) before optimize() returns
        self._ckpt_shutdown(raise_errors=True)
        self._stop_profiler()
        self._publish_expert_telemetry(mstate)
        if su is not None:
            params = su.gather_params(params)
        elif pp is not None:
            params = pp.gather_params(params)
        model.sync(params, mstate)
        model.evaluate()
        return model

"""Training loops, optim methods, triggers, validation (reference:
dl/.../bigdl/optim/)."""

from bigdl_tpu.optim.optim_method import (OptimMethod, Adagrad, Adam,
                                          AdamW, LBFGS)
from bigdl_tpu.optim.sgd import (SGD, Default, Step, EpochStep, EpochDecay,
                                 Poly, Regime, EpochSchedule, Warmup,
                                 CosineAnnealing)
from bigdl_tpu.optim.trigger import (Trigger, every_epoch, several_iteration,
                                     max_epoch, max_iteration, min_loss,
                                     or_trigger, and_trigger)
from bigdl_tpu.optim.validation import (ValidationMethod, ValidationResult,
                                        AccuracyResult, LossResult,
                                        Top1Accuracy, Top5Accuracy, Loss)
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.remat import known_remat_policies
from bigdl_tpu.optim.optimizer import Optimizer, LocalOptimizer
from bigdl_tpu.optim.validator import (Validator, LocalValidator,
                                       DistriValidator)
from bigdl_tpu.optim.predictor import Predictor

"""Optimizer facade + LocalOptimizer.

Reference parity: abstract Optimizer (optim/Optimizer.scala:29-128 —
setValidation / setCheckpoint / setState / setOptimMethod / setEndWhen /
overWriteCheckpoint), factory dispatch on dataset type (:150-186), and
LocalOptimizer (optim/LocalOptimizer.scala:39-242).

TPU-first: the reference clones one model per core, shares a flat weight
storage, runs thread-parallel fwd/bwd and merges gradients chunk-parallel
(:64-141). All of that collapses into ONE jit-compiled train step — XLA owns
op parallelism on the chip; there are no replicas to merge. The step fn is
donated-argument jitted so weights update in place in HBM.
"""
from __future__ import annotations

import logging
import time

import jax
import jax.numpy as jnp

from bigdl_tpu.dataset.dataset import (AbstractDataSet, ShardedDataSet,
                                       to_jax_batch)
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.optim_method import OptimMethod
from bigdl_tpu.optim.sgd import SGD
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.utils.table import Table, T

logger = logging.getLogger("bigdl_tpu.optim")

__all__ = ["Optimizer", "LocalOptimizer"]


class Optimizer:
    """Facade + factory (reference optim/Optimizer.scala)."""

    def __new__(cls, model=None, dataset=None, criterion=None,
                batch_size=None, **kw):
        if cls is Optimizer:
            # factory dispatch (reference Optimizer.apply :150-186); the
            # is_sharded() walk sees through transform wrappers
            sharded = dataset is not None and hasattr(dataset, "is_sharded") \
                and dataset.is_sharded()
            if sharded or kw.get("mesh") is not None:
                from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
                return super().__new__(DistriOptimizer)
            return super().__new__(LocalOptimizer)
        return super().__new__(cls)

    def __init__(self, model, dataset, criterion, batch_size=None, **kw):
        from bigdl_tpu.dataset.transformer import SampleToBatch
        from bigdl_tpu.dataset.sample import Sample
        self.model = model
        if batch_size is not None:
            # RDD[Sample]+batchSize overload (reference :150-162)
            dataset = dataset >> SampleToBatch(batch_size)
        self.dataset = dataset
        self.criterion = criterion
        self.state = T()
        self.optim_method: OptimMethod = SGD()
        self.end_when: Trigger | None = None
        self.validation_trigger = None
        self.validation_dataset = None
        self.validation_methods = None
        self.checkpoint_trigger = None
        self.checkpoint_path = None
        self.is_overwrite = False
        self.metrics = Metrics()

    # -- builder API (reference Optimizer.scala:66-123) --
    def set_validation(self, trigger, dataset, methods):
        self.validation_trigger = trigger
        self.validation_dataset = dataset
        self.validation_methods = list(methods)
        return self

    def set_checkpoint(self, path, trigger):
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger
        return self

    def overwrite_checkpoint(self):
        self.is_overwrite = True
        return self

    def set_state(self, state):
        self.state = Table(state)
        return self

    def set_optim_method(self, method: OptimMethod):
        self.optim_method = method
        return self

    def set_end_when(self, end_when: Trigger):
        self.end_when = end_when
        return self

    def optimize(self):
        raise NotImplementedError

    # -- shared helpers --
    def _header(self, epoch, count, total, neval, wallclock):
        """(reference Optimizer.header, Optimizer.scala:131-134)"""
        return f"[Epoch {epoch} {count}/{total}][Iteration {neval}]" \
               f"[Wall Clock {wallclock:.3f}s]"

    def _validate(self, apply_fn, params, mstate, driver_state):
        if self.validation_trigger is None or \
                self.validation_dataset is None:
            return None
        if not self.validation_trigger(driver_state):
            return None
        results = [None] * len(self.validation_methods)
        count = 0
        t0 = time.perf_counter()
        for batch in self.validation_dataset.data(train=False):
            data, labels = to_jax_batch(batch)
            out = apply_fn(params, mstate, data)
            count += data.shape[0]
            for i, m in enumerate(self.validation_methods):
                r = m(out, labels)
                results[i] = r if results[i] is None else results[i] + r
        elapsed = time.perf_counter() - t0
        logger.info(f"validate model throughput is "
                    f"{count / max(elapsed, 1e-9):.2f} records/second")
        for m, r in zip(self.validation_methods, results):
            logger.info(f"{m!r} is {r!r}")
        return dict(zip([repr(m) for m in self.validation_methods], results))

    def _checkpoint(self, driver_state):
        if self.checkpoint_trigger is None or self.checkpoint_path is None:
            return
        if not self.checkpoint_trigger(driver_state):
            return
        from bigdl_tpu.utils import file as _file
        neval = driver_state["neval"]
        suffix = "" if self.is_overwrite else f".{neval}"
        _file.save_module(self.model,
                          f"{self.checkpoint_path}/model{suffix}",
                          overwrite=True)
        _file.save(dict(driver_state),
                   f"{self.checkpoint_path}/state{suffix}", overwrite=True)
        logger.info(f"Save model to {self.checkpoint_path}/model{suffix}")


class LocalOptimizer(Optimizer):
    """Single-host training loop (reference optim/LocalOptimizer.scala)."""

    def optimize(self):
        model, criterion, optim = self.model, self.criterion, \
            self.optim_method
        model.materialize()
        model.training()
        params, mstate = model.params, model.state
        opt_state = optim.init_state(params)
        # resume support (reference: epoch/neval live in the state Table,
        # DistriOptimizer.scala:80-81)
        driver_state = {"epoch": int(self.state.get("epoch", 1)),
                        "neval": int(self.state.get("neval", 1)),
                        "is_epoch_end": False, "loss": float("inf")}
        if driver_state["neval"] > 1:
            opt_state["neval"] = jnp.asarray(driver_state["neval"] - 1,
                                             jnp.int32)

        def train_step(params, mstate, opt_state, rng, data, labels, epoch):
            def loss_fn(p):
                y, new_mstate = model.apply(p, mstate, data, training=True,
                                            rng=rng)
                return criterion.apply(y, labels), new_mstate

            (loss, new_mstate), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            opt_state = dict(opt_state, epoch=epoch)
            new_params, new_opt_state = optim.update(grads, params,
                                                     opt_state)
            return new_params, new_mstate, new_opt_state, loss

        jit_step = jax.jit(train_step, donate_argnums=(0, 1, 2))

        def eval_apply(params, mstate, data):
            out, _ = model.apply(params, mstate, data, training=False)
            return out

        jit_eval = jax.jit(eval_apply)

        rng = jax.random.PRNGKey(int(self.state.get("seed", 0)))
        data_iter = self.dataset.data(train=True)
        epoch_size = self.dataset.size()
        count_this_epoch = int(self.state.get("record_count", 0))
        wallclock_start = time.perf_counter()

        while self.end_when is None or not self.end_when(driver_state):
            driver_state["is_epoch_end"] = False
            t0 = time.perf_counter()
            batch = next(data_iter)
            data, labels = to_jax_batch(batch)
            data_time = time.perf_counter() - t0
            rng, step_rng = jax.random.split(rng)
            params, mstate, opt_state, loss = jit_step(
                params, mstate, opt_state, step_rng, data, labels,
                jnp.asarray(driver_state["epoch"], jnp.int32))
            loss = float(loss)  # blocks; keeps host loop in lockstep
            step_time = time.perf_counter() - t0
            n = int(data.shape[0])
            count_this_epoch += n
            driver_state["loss"] = loss
            wallclock = time.perf_counter() - wallclock_start
            logger.info(
                self._header(driver_state["epoch"], count_this_epoch,
                             epoch_size, driver_state["neval"], wallclock)
                + f" loss is {loss:.6f}, iteration time is {step_time:.4f}s,"
                f" data fetch time is {data_time:.4f}s, "
                f"throughput is {n / max(step_time, 1e-9):.2f} records/second")
            self.metrics.set("computing time for each iteration", step_time)
            self.metrics.set("data fetch time", data_time)
            driver_state["neval"] += 1
            if count_this_epoch >= epoch_size:
                driver_state["epoch"] += 1
                driver_state["is_epoch_end"] = True
                count_this_epoch = 0
                self.dataset.shuffle()
                data_iter = self.dataset.data(train=True)
            # publish params for validation/checkpoint (rebinds children
            # too — the old buffers were donated to the jitted step)
            model.sync(params, mstate)
            self._validate(jit_eval, params, mstate, driver_state)
            self._checkpoint(driver_state)

        model.sync(params, mstate)
        model.evaluate()
        return model

"""Optimizer facade + LocalOptimizer.

Reference parity: abstract Optimizer (optim/Optimizer.scala:29-128 —
setValidation / setCheckpoint / setState / setOptimMethod / setEndWhen /
overWriteCheckpoint), factory dispatch on dataset type (:150-186), and
LocalOptimizer (optim/LocalOptimizer.scala:39-242).

TPU-first: the reference clones one model per core, shares a flat weight
storage, runs thread-parallel fwd/bwd and merges gradients chunk-parallel
(:64-141). All of that collapses into ONE jit-compiled train step — XLA owns
op parallelism on the chip; there are no replicas to merge. The step fn is
donated-argument jitted so weights update in place in HBM.
"""
from __future__ import annotations

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.dataset.dataset import (to_jax_batch)
from bigdl_tpu.observability import compile_watch, trace
from bigdl_tpu.observability.flight_recorder import FlightRecorder
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.optim_method import OptimMethod
from bigdl_tpu.optim.sgd import SGD
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.utils.table import Table, T

logger = logging.getLogger("bigdl_tpu.optim")

__all__ = ["Optimizer", "LocalOptimizer"]


def _clip_gradients(grads, clip):
    """Global-L2 and/or constant clipping, traced into the train step."""
    if not clip:
        return grads
    if clip["min_value"] is not None:
        grads = jax.tree.map(
            lambda g: jnp.clip(g, clip["min_value"], clip["max_value"]),
            grads)
    if clip["l2_norm"] is not None:
        norm = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                            for g in jax.tree.leaves(grads)))
        scale = jnp.minimum(1.0, clip["l2_norm"] / (norm + 1e-12))
        grads = jax.tree.map(lambda g: g * scale, grads)
    return grads


def _require_process_sharded(dataset, what: str):
    """Multi-host evaluation double-counts unless each process holds its
    OWN shard: refuse unsharded datasets, shard counts that don't match
    the process count, and duplicate shard indices (e.g. every process
    left shard_index at the default 0 — round-5 review findings).

    COLLECTIVE: gathers every process's local view FIRST so all hosts
    reach the same verdict from the same data — a host-local raise while
    peers proceed into a later collective would hang the job."""
    from bigdl_tpu.parallel.collective import process_allgather_pyobj
    sharded = hasattr(dataset, "is_sharded") and dataset.is_sharded()
    count_fn = getattr(dataset, "process_shard_count", None)
    idx_fn = getattr(dataset, "process_shard_index", None)
    infos = process_allgather_pyobj(
        (bool(sharded), count_fn() if count_fn is not None else None,
         idx_fn() if idx_fn is not None else None))
    nproc = jax.process_count()
    if not all(s for s, _, _ in infos):
        raise ValueError(
            f"multi-host evaluation requires a process-sharded {what} "
            f"(each of the {nproc} processes must hold its own shard); "
            "an unsharded dataset would be double-counted in the "
            "cross-host reduce")
    bad = {c for _, c, _ in infos if c is not None and c != nproc}
    if bad:
        raise ValueError(
            f"{what} was built for {sorted(bad)} process shards but the "
            f"job has {nproc} processes — the cross-host reduce would "
            "mis-count")
    indices = [i for _, _, i in infos if i is not None]
    if len(indices) == len(infos) and len(set(indices)) != len(indices):
        raise ValueError(
            f"{what} shard indices {indices} are not distinct across "
            "processes (every process must pass its own process_index, "
            "not the default) — duplicated shards would be "
            "double-counted and the rest never evaluated")


class Optimizer:
    """Facade + factory (reference optim/Optimizer.scala)."""

    def __new__(cls, model=None, dataset=None, criterion=None,
                batch_size=None, **kw):
        if cls is Optimizer:
            # factory dispatch (reference Optimizer.apply :150-186); the
            # is_sharded() walk sees through transform wrappers
            sharded = dataset is not None and hasattr(dataset, "is_sharded") \
                and dataset.is_sharded()
            if sharded or kw.get("mesh") is not None:
                from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
                return super().__new__(DistriOptimizer)
            return super().__new__(LocalOptimizer)
        return super().__new__(cls)

    def __init__(self, model, dataset, criterion, batch_size=None, *,
                 remat_policy: str | None = None,
                 grad_accumulation: int = 1,
                 pipeline_stages: int = 1,
                 pipeline_schedule: str = "1f1b",
                 pipeline_virtual_stages: int = 1,
                 expert_parallel: bool | str = False,
                 expert_aux_weight: float = 1e-2, **kw):
        from bigdl_tpu.dataset.transformer import SampleToBatch
        from bigdl_tpu.optim.remat import check_remat_policy
        self.model = model
        if batch_size is not None:
            # RDD[Sample]+batchSize overload (reference :150-162)
            dataset = dataset >> SampleToBatch(batch_size)
        self.dataset = dataset
        self.criterion = criterion
        self.state = T()
        self.optim_method: OptimMethod = SGD()
        self.end_when: Trigger | None = None
        self.validation_trigger = None
        self.validation_dataset = None
        self.validation_methods = None
        self.checkpoint_trigger = None
        self.checkpoint_path = None
        self.is_overwrite = False
        # async checkpointing (bigdl_tpu/elastic/, docs/ELASTICITY.md):
        # _checkpoint snapshots device state with one packed device_get
        # and hands serialization to a background CheckpointWriter; the
        # loops barrier at epoch end and drain it at exit. receipt =
        # handoff_s vs write_s split after the run.
        self.checkpoint_async = True
        self.checkpoint_keep = None
        self._ckpt_writer = None
        self._ckpt_mesh = None
        self.checkpoint_receipt = None
        self.metrics = Metrics()
        # per-epoch input-wait accounting (host-side span timers only;
        # step_time already contains data_time, so the fraction is
        # wait / total — reset at each epoch boundary)
        self._epoch_wait_s = 0.0
        self._epoch_total_s = 0.0
        self.profile_dir = None
        self.profile_start = 0
        self.profile_iters = 0
        self._profiling = False
        self.grad_clip = None
        self.input_transform = None
        # memory-for-throughput knobs (optim/remat.py,
        # optim/accumulation.py, docs/PERFORMANCE.md): a named
        # jax.checkpoint policy applied to the model forward at step
        # construction, and the number of microbatches one compiled
        # step scans with the gradient accumulated before the single
        # optimizer update. Both are AOT-cache key material.
        self.remat_policy = check_remat_policy(remat_policy)
        self.grad_accumulation = self._check_grad_accumulation(
            grad_accumulation)
        # pipeline + expert parallelism (parallel/pipeline.py,
        # parallel/expert.py, docs/PERFORMANCE.md): stage count/schedule
        # for Sequential stacks over a 'pipe' mesh axis, and the MoE
        # aux-loss/telemetry wiring for models carrying MoE layers over
        # an 'expert' mesh axis. All of it is AOT-cache key material.
        self.pipeline_stages = 1
        self.pipeline_schedule = "1f1b"
        self.pipeline_virtual_stages = 1
        if pipeline_stages != 1 or pipeline_virtual_stages != 1 \
                or pipeline_schedule != "1f1b":
            self.set_pipeline(pipeline_stages,
                              schedule=pipeline_schedule,
                              virtual_stages=pipeline_virtual_stages)
        self.expert_parallel = None
        self.expert_aux_weight = float(expert_aux_weight)
        if expert_parallel:
            self.set_expert_parallel(expert_parallel,
                                     aux_weight=expert_aux_weight)
        self.train_summary = None
        self.val_summary = None
        # async dispatch: how many steps may be in flight before the loop
        # drains their losses with one packed readback (docs/PERFORMANCE.md)
        self.max_in_flight = 2
        # fully sharded weight update + wire-compressed collectives
        # (optim/sharded_update.py, docs/PERFORMANCE.md): active on the
        # distributed path; the local single-program path has no
        # collectives, so the setting is accepted and inert there
        self.shard_weight_update = False
        self.wire_codec = None
        # None = resolve per run: the autotuned record for this
        # (param count, shard count) when one exists, else the 4 MB
        # default (optim/sharded_update.py tuned_bucket_mb)
        self.bucket_mb = None
        # persistent AOT executable cache (tuning/aot_cache.py):
        # "env" = $BIGDL_TPU_AOT_CACHE_DIR when set, else off;
        # set_aot_cache() overrides either way
        self._aot_cache_cfg = "env"
        # overlapped input pipeline (dataset/prefetch.py): batches are
        # assembled + device-placed on a worker thread, `depth` ahead of
        # the loop; 0 = the synchronous path (docs/PERFORMANCE.md)
        self.prefetch_depth = 2
        self.pad_partial_batches = False
        self._pad_stage = None
        self._epoch_position_state = None
        # telemetry plane (docs/OBSERVABILITY.md): the flight recorder's
        # black box is ON by default (steady-state cost: a deque append
        # per warning/span event); the HTTP exporter is opt-in
        self.flight_recorder: FlightRecorder | None = FlightRecorder()
        self._metrics_server_cfg = None
        self._metrics_server = None
        self._liveness_deadline = 600.0
        self._last_step_mono = None
        self._liveness_registered = False

    # -- builder API (reference Optimizer.scala:66-123) --
    def set_validation(self, trigger, dataset, methods):
        self.validation_trigger = trigger
        self.validation_dataset = dataset
        self.validation_methods = list(methods)
        return self

    def set_checkpoint(self, path, trigger, *, async_save: bool = True,
                       keep: int | None = None):
        """Checkpoint the full training state to ``path`` on ``trigger``
        (reference Optimizer.setCheckpoint). The directory is validated
        EAGERLY — created if absent, write-probed — so a bad path fails
        here, not minutes into training at the first trigger fire.

        ``async_save=True`` (default) serializes checkpoints on a
        background writer thread (bigdl_tpu/elastic/, saved bytes
        bit-identical to the synchronous path); ``False`` restores the
        fully synchronous save. ``keep=K`` enables retention GC
        (``elastic.manifest.sweep_checkpoints``): after each manifest
        commit only the newest K numbered checkpoints survive, and
        torn/orphaned member files from never-committed manifests are
        swept — long runs stop filling the store (ROADMAP 1(c)).
        Ignored under ``overwrite_checkpoint`` (one unsuffixed
        snapshot, nothing to retain)."""
        from bigdl_tpu.utils.file import ensure_writable_dir
        ensure_writable_dir(path)
        if keep is not None and keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger
        self.checkpoint_async = bool(async_save)
        self.checkpoint_keep = keep
        return self

    def overwrite_checkpoint(self):
        self.is_overwrite = True
        return self

    def set_state(self, state):
        self.state = Table(state)
        return self

    def set_gradient_clipping(self, *, l2_norm: float | None = None,
                              min_value: float | None = None,
                              max_value: float | None = None):
        """Clip gradients inside the jitted train step: by global L2 norm
        (transformer-era staple) and/or constant min/max (the clipping
        style later BigDL releases expose). Applies to Local and Distri
        optimizers alike; returns self."""
        if l2_norm is None and min_value is None and max_value is None:
            raise ValueError(
                "set_gradient_clipping needs l2_norm and/or "
                "min_value+max_value")
        if l2_norm is not None and l2_norm <= 0:
            raise ValueError(f"l2_norm must be > 0, got {l2_norm}")
        if ((min_value is None) != (max_value is None)):
            raise ValueError("min_value and max_value must be set together")
        if min_value is not None and min_value >= max_value:
            raise ValueError(f"min_value {min_value} must be < "
                             f"max_value {max_value}")
        self.grad_clip = {"l2_norm": l2_norm, "min_value": min_value,
                          "max_value": max_value}
        return self

    def set_optim_method(self, method: OptimMethod):
        self.optim_method = method
        return self

    def set_train_summary(self, summary):
        """Per-iteration scalar event log (reference-parity
        ``TrainSummary``, observability/summary.py): the loop appends
        Loss / Throughput / HostInputTime / DeviceStepTime for every
        step, emitted at window-drain time under the step's original
        ``neval`` (docs/PERFORMANCE.md). Host floats only — recording
        never adds a device sync the loop wasn't already paying.
        Returns self."""
        self.train_summary = summary
        return self

    def set_val_summary(self, summary):
        """``ValidationSummary`` event log: one scalar per validation
        method per validation pass, tagged by the method's repr, plus
        ValidationThroughput. Returns self."""
        self.val_summary = summary
        return self

    def set_input_transform(self, fn):
        """Pure function applied to each batch's DATA inside the jitted
        train/eval step — the hook the u8 input pipeline uses to run
        normalize/BGR/NCHW on-device
        (``dataset.image.device_transform.u8_to_model_input``) so the host
        ships raw uint8 crops (4x smaller transfers) and the reference's
        host-side BGRImgNormalizer work rides the TPU. Returns self."""
        self.input_transform = fn
        return self

    def set_async_dispatch(self, max_in_flight: int = 2):
        """Bound how far the train loop's dispatch pipeline may run ahead
        of the host before draining the pending losses with ONE packed
        ``jax.device_get``. ``max_in_flight=1`` is the classic lockstep
        loop (a readback every iteration); larger windows let XLA's async
        dispatch overlap host-input work with device steps at the cost of
        the logged loss lagging ``neval`` by up to the window
        (docs/PERFORMANCE.md). Triggers whose ``requires`` includes
        ``"loss"`` (``min_loss``) force lockstep regardless. Returns
        self."""
        if int(max_in_flight) < 1:
            raise ValueError(
                f"max_in_flight must be >= 1, got {max_in_flight}")
        self.max_in_flight = int(max_in_flight)
        return self

    def set_input_pipeline(self, depth: int = 2, *,
                           pad_partial_batches: bool | None = None):
        """Configure the overlapped input pipeline
        (``dataset/prefetch.py``, docs/PERFORMANCE.md). ``depth`` >= 1
        runs ``next(batch)`` + transforms + device placement on a
        prefetch worker, ``depth`` batches ahead of the train loop, so
        the loop's input phase is a queue pop (the ``input wait``
        span); ``depth=0`` restores the synchronous path. On by
        default (depth 2) — trajectories are bit-identical either way
        (tests/test_prefetch.py pins it).

        ``pad_partial_batches=True`` additionally pads each pass's
        final short batch to the full batch shape with an in-step
        validity mask (``nn.MaskedCriterion``): one compiled train-step
        signature per run instead of one per distinct batch shape, with
        padded rows contributing exactly zero to loss and gradient.
        Returns self."""
        if int(depth) < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {depth}")
        self.prefetch_depth = int(depth)
        if pad_partial_batches is not None:
            self.pad_partial_batches = bool(pad_partial_batches)
        return self

    def set_end_when(self, end_when: Trigger):
        self.end_when = end_when
        return self

    @staticmethod
    def _check_grad_accumulation(k) -> int:
        if int(k) < 1:
            raise ValueError(f"num_microbatches must be >= 1, got {k}")
        return int(k)

    def set_remat_policy(self, policy: str | None):
        """Select the activation-rematerialization policy applied to
        the model forward when the train step is constructed
        (optim/remat.py, docs/PERFORMANCE.md): ``"none"`` (default,
        save every residual), ``"dots_saveable"`` (save matmul/conv
        outputs), ``"per_block"`` (checkpoint each top-level block of a
        Sequential stack — the selective policy for transformer/
        inception stacks), ``"nothing_saveable"`` (save only region
        inputs; maximum HBM savings, one forward of recompute). Loss
        and gradients are BIT-identical across policies — only peak
        activation memory and recompute move. The policy keys the AOT
        executable cache, so switching it misses correctly. Returns
        self."""
        from bigdl_tpu.optim.remat import check_remat_policy
        self.remat_policy = check_remat_policy(policy)
        return self

    def set_grad_accumulation(self, num_microbatches: int = 1):
        """Compile the train step to ``lax.scan`` ``num_microbatches``
        microbatches through forward/backward with gradients
        accumulated on device, then run the optimizer update (and, on
        the sharded-update path, the bucketed gradient reduce-scatter)
        EXACTLY ONCE per step (optim/accumulation.py,
        docs/PERFORMANCE.md). The loop still feeds full batches; the
        split is internal and strided, so an effectively k×-larger
        batch runs at near-constant peak activation HBM.
        ``num_microbatches=1`` IS the plain step — same construction,
        same AOT cache key. The batch must divide by k (refused loudly
        at step construction otherwise). Returns self."""
        self.grad_accumulation = self._check_grad_accumulation(
            num_microbatches)
        return self

    def set_pipeline(self, num_stages: int, *, schedule: str = "1f1b",
                     virtual_stages: int = 1):
        """Partition a ``Sequential`` model's top-level blocks into
        ``num_stages`` pipeline stages over the mesh ``pipe`` axis and
        compile ONE train step that scans the combined forward/backward
        schedule (``"gpipe"`` / ``"1f1b"`` / ``"interleaved_1f1b"``;
        parallel/pipeline.py, docs/PERFORMANCE.md).
        ``set_grad_accumulation(M)`` sets the microbatch count the
        schedule streams — the optimizer update still fires exactly once
        per step, and the trained trajectory matches the non-pipelined
        accumulated step (tests/test_pipeline_train.py pins it
        bit-identical on the pure-pipe mesh). ``virtual_stages > 1``
        (interleaved schedule only) assigns each device that many
        round-robin chunks, shrinking the bubble fraction from
        (S-1)/(M+S-1) to (S-1)/(v·M+S-1). Distributed path only: the
        local optimizer has no mesh to pipeline over. The knobs key the
        AOT executable cache. Returns self."""
        from bigdl_tpu.parallel.pipeline import check_pipeline_schedule
        if int(num_stages) < 1:
            raise ValueError(
                f"pipeline_stages must be >= 1, got {num_stages}")
        if int(virtual_stages) < 1:
            raise ValueError(
                f"virtual_stages must be >= 1, got {virtual_stages}")
        self.pipeline_stages = int(num_stages)
        self.pipeline_schedule = check_pipeline_schedule(schedule)
        self.pipeline_virtual_stages = int(virtual_stages)
        return self

    def set_expert_parallel(self, axis: bool | str = True, *,
                            aux_weight: float = 1e-2):
        """Wire the model's MoE layers (parallel/expert.py ``MoE``) into
        the training objective: the load-balancing aux loss the layers
        stash in module state joins the criterion with weight
        ``aux_weight``, and the dispatch telemetry (token drops,
        overflow, load imbalance) is published to the metric registry at
        epoch boundaries — one batched readback per epoch, never a
        per-step sync. ``axis`` names the mesh axis experts shard over
        (True = ``"expert"``); the mesh must carry it. Keys the AOT
        executable cache. Returns self."""
        if aux_weight < 0:
            raise ValueError(
                f"aux_weight must be >= 0, got {aux_weight}")
        self.expert_parallel = ("expert" if axis is True else axis) \
            if axis else None
        self.expert_aux_weight = float(aux_weight)
        return self

    def _aux_loss_fn(self):
        """The aux-loss hook ``make_train_step`` folds into the
        objective (None when expert parallelism is off)."""
        if not self.expert_parallel:
            return None
        from bigdl_tpu.parallel.expert import moe_aux_total
        w = self.expert_aux_weight

        def aux(new_mstate):
            return w * moe_aux_total(new_mstate)

        return aux

    def set_sharded_update(self, enabled: bool = True, *,
                          wire_codec=None, bucket_mb: float | None = None):
        """Configure the fully cross-replica-sharded weight update
        (optim/sharded_update.py, docs/PERFORMANCE.md): reduce-scatter
        gradients in size-targeted buckets, update parameters +
        optimizer state 1/N per replica, all-gather updated parameters.

        ``wire_codec``: ``None`` keeps implicit full-width collectives
        (trajectories bit-identical to the replicated update);
        ``"fp32"``/``"bf16"``/``"int8"`` switch to explicit per-shard
        collectives at that wire width — ``"bf16"`` is the reference's
        FP16 wire, ``"int8"`` adds stochastic rounding + error feedback
        (the residual rides the optimizer state and checkpoints).
        ``bucket_mb`` targets the per-bucket payload the backward
        overlaps against. Only the distributed optimizer has
        collectives; on the local path this is accepted and inert.
        Returns self."""
        from bigdl_tpu.parameters.compression import get_codec
        get_codec(wire_codec)          # validate the name eagerly
        self.shard_weight_update = bool(enabled) or wire_codec is not None
        self.wire_codec = wire_codec
        if bucket_mb is not None:
            if bucket_mb <= 0:
                raise ValueError(f"bucket_mb must be > 0, got {bucket_mb}")
            self.bucket_mb = float(bucket_mb)
        return self

    def set_aot_cache(self, cache):
        """Configure the persistent AOT executable cache
        (``tuning/aot_cache.py``, docs/PERFORMANCE.md): train-step
        construction becomes an explicit lower → compile → cache
        pipeline, and a restarting worker whose cache directory is warm
        LOADS its compiled step (~ms) instead of recompiling it
        (seconds to minutes) — results are bit-identical either way,
        and any unreadable/stale entry falls back to a fresh compile.

        ``cache``: a directory path, an ``AOTCache``, or ``None`` to
        disable (overriding ``$BIGDL_TPU_AOT_CACHE_DIR``, which
        otherwise applies when this method was never called). Returns
        self."""
        if isinstance(cache, str):
            from bigdl_tpu.tuning.aot_cache import AOTCache
            cache = AOTCache(cache)
        self._aot_cache_cfg = cache
        return self

    def _aot_cache(self):
        """The effective cache for this run (None = caching off)."""
        if self._aot_cache_cfg == "env":
            from bigdl_tpu.tuning.aot_cache import env_cache
            return env_cache()
        return self._aot_cache_cfg

    def _step_key_extra(self) -> tuple:
        """Program-identity key material for the AOT executable cache.
        The abstract shape signature alone cannot tell two programs
        with identical shapes apart, and jit-constant hyperparameters
        (learning rate, clip bounds, dtype policy) are compiled into
        the executable — so they all key the cache. ``stable_repr``
        strips object addresses so the material matches across worker
        processes."""
        from bigdl_tpu.tensor import get_policy
        from bigdl_tpu.tuning.aot_cache import stable_repr
        optim = self.optim_method
        transform = None
        if self.input_transform is not None:
            fn = self.input_transform
            transform = getattr(fn, "__qualname__", None) or repr(fn)
            try:        # a lambda's qualname alone would collide
                import hashlib
                import inspect
                transform += ":" + hashlib.sha1(
                    inspect.getsource(fn).encode()).hexdigest()[:12]
            except Exception:
                pass
        return (stable_repr(self.model), stable_repr(self.criterion),
                type(optim).__name__, stable_repr(vars(optim)),
                stable_repr(self.grad_clip), stable_repr(get_policy()),
                transform, self._pad_stage is not None,
                self.shard_weight_update, self.wire_codec,
                self.bucket_mb,
                getattr(self, "tensor_parallel", None),
                getattr(self, "sequence_parallel", None),
                getattr(self, "shard_optim_state", None),
                # remat + accumulation change the compiled program at
                # identical shapes — they must miss the cache; k=1 and
                # policy "none" ARE the plain step (same key as a run
                # that never configured them)
                self.remat_policy, self.grad_accumulation,
                # pipeline schedule/stages and the MoE aux wiring also
                # change the program at identical shapes
                # (tests/test_pipeline_train.py pins the miss)
                self.pipeline_stages, self.pipeline_schedule,
                self.pipeline_virtual_stages, self.expert_parallel,
                self.expert_aux_weight if self.expert_parallel
                else None)

    def set_metrics_server(self, port: int = 0, host: str = "127.0.0.1",
                           *, liveness_deadline: float = 600.0):
        """Expose the live telemetry plane over HTTP for the duration
        of :meth:`optimize`: /metrics (Prometheus text), /metrics.json,
        /trace, /healthz, /readyz (docs/OBSERVABILITY.md). ``port=0``
        binds an ephemeral port — read it from
        ``self._metrics_server.port`` once training starts. A
        ``training_liveness`` health check reports failing when no step
        has progressed within ``liveness_deadline`` seconds (warming up
        before the first step counts as live). Returns self."""
        if liveness_deadline <= 0:
            raise ValueError(f"liveness_deadline must be > 0, got "
                             f"{liveness_deadline}")
        self._metrics_server_cfg = {"port": int(port), "host": host}
        self._liveness_deadline = float(liveness_deadline)
        return self

    def set_flight_recorder(self, recorder=None):
        """Replace the default crash flight recorder: pass a
        :class:`FlightRecorder`, a directory path (a recorder dumping
        there), or None to disable. On by default — an optimizer run
        that dies leaves a postmortem directory (registry JSON, trace
        JSON, last-N events, compile ledger, exception). Returns
        self."""
        if isinstance(recorder, str):
            recorder = FlightRecorder(dir=recorder)
        self.flight_recorder = recorder
        return self

    # -- telemetry plane lifecycle (docs/OBSERVABILITY.md) --
    def _liveness_check(self):
        last = self._last_step_mono
        if last is None:
            return True, "no step yet (warming up)"
        age = time.monotonic() - last
        return (age <= self._liveness_deadline,
                f"last step {age:.1f}s ago "
                f"(deadline {self._liveness_deadline:.0f}s)")

    def _telemetry_step(self) -> None:
        """Heartbeat: one monotonic read per iteration, feeding the
        training_liveness health check."""
        self._last_step_mono = time.monotonic()

    def _telemetry_start(self) -> None:
        self._last_step_mono = None
        if self.flight_recorder is not None:
            self.flight_recorder.install()
        from bigdl_tpu.observability.exporter import default_health
        default_health().register("training_liveness",
                                  self._liveness_check, kind="liveness")
        self._liveness_registered = True
        if self._metrics_server_cfg is not None:
            from bigdl_tpu.observability.exporter import MetricsServer
            cfg = self._metrics_server_cfg
            self._metrics_server = MetricsServer(cfg["port"],
                                                 cfg["host"]).start()
            logger.info("telemetry plane listening on %s "
                        "(/metrics /metrics.json /trace /healthz "
                        "/readyz)", self._metrics_server.url)

    def _telemetry_stop(self) -> None:
        if self._liveness_registered:
            from bigdl_tpu.observability.exporter import default_health
            default_health().unregister("training_liveness")
            self._liveness_registered = False
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None
        if self.flight_recorder is not None:
            self.flight_recorder.uninstall()

    def optimize(self):
        """Run the training loop with the telemetry plane armed: the
        metrics server (when configured) and the training-liveness
        check span the run, and ANY escaping exception leaves a
        postmortem directory before propagating — the loop may be
        wrapped in a driver that catches it, where ``sys.excepthook``
        would never fire."""
        self._telemetry_start()
        try:
            return self._optimize_impl()
        except BaseException as e:
            if self.flight_recorder is not None:
                self.flight_recorder.dump_postmortem(
                    e, reason="optimizer exception")
            raise
        finally:
            # failure path: drain/stop the async checkpoint writer
            # without masking the original exception (the success path
            # already shut it down, raising on background save errors)
            self._ckpt_shutdown(raise_errors=False)
            self._telemetry_stop()

    def _optimize_impl(self):
        raise NotImplementedError

    # -- shared helpers --
    def _header(self, epoch, count, total, neval, wallclock):
        """(reference Optimizer.header, Optimizer.scala:131-134)"""
        return f"[Epoch {epoch} {count}/{total}][Iteration {neval}]" \
               f"[Wall Clock {wallclock:.3f}s]"

    def _record_step(self, neval: int, loss: float, n: int,
                     step_time: float, data_time: float,
                     device_time: float) -> None:
        """Shared per-iteration observability: the honest host-side
        phase split into Metrics (-> registry histograms) plus the
        TrainSummary event log. Called at DRAIN time with the step's
        original ``neval`` stamp — ``loss`` is already a host float;
        everything here is host arithmetic."""
        self.metrics.record("device step time", device_time)
        self.metrics.record("host input time", data_time)
        self._epoch_wait_s += data_time
        self._epoch_total_s += step_time
        if self.train_summary is not None:
            s = self.train_summary
            s.add_scalar("Loss", loss, neval)
            s.add_scalar("Throughput", n / max(step_time, 1e-9), neval)
            s.add_scalar("HostInputTime", data_time, neval)
            s.add_scalar("DeviceStepTime", device_time, neval)

    def _emit_input_wait_fraction(self, neval: int) -> None:
        """Epoch-end roll-up of the per-step host-side span timers: what
        fraction of the epoch's wall time the consumer spent waiting on
        input. Pure host arithmetic over already-collected floats — no
        device sync — labeled per host by the shard-tagged starvation
        metrics it complements (dataset/prefetch.py)."""
        if self._epoch_total_s <= 0:
            return
        frac = min(1.0, self._epoch_wait_s / self._epoch_total_s)
        self.metrics.set("input wait fraction", frac)
        if self.train_summary is not None:
            self.train_summary.add_scalar("InputWaitFraction", frac, neval)
        self._epoch_wait_s = 0.0
        self._epoch_total_s = 0.0

    def _validate(self, apply_fn, params, mstate, driver_state, *,
                  fire: bool | None = None):
        """``fire``: pre-evaluated trigger decision from :meth:`_fires`;
        None (direct callers/tests) evaluates the trigger here."""
        if fire is None:
            if self.validation_trigger is None or \
                    self.validation_dataset is None:
                return None
            fire = self.validation_trigger(driver_state)
        if not fire:
            return None
        if jax.process_count() > 1:
            _require_process_sharded(self.validation_dataset,
                                     "validation dataset")
            # multi-host: gather params/state to host ONCE per validation
            # pass (a collective — safe: the fire decision is a
            # deterministic function of the shared driver state, and it
            # runs once per pass regardless of per-process batch counts);
            # apply_fn then evaluates on local devices
            from bigdl_tpu.utils.file import _to_host
            params, mstate = _to_host(params), _to_host(mstate)
        results = [None] * len(self.validation_methods)
        count = 0
        t0 = time.perf_counter()
        # in-training validation rides the same prefetch machinery as
        # the train loop: batch assembly (transforms, stacking) overlaps
        # eval dispatch on a worker thread (dataset/prefetch.py)
        from bigdl_tpu.dataset.prefetch import open_input_pipeline
        val_iter = open_input_pipeline(
            self.validation_dataset.data(train=False),
            depth=self.prefetch_depth, name="val",
            # validating ON the training set is legal: the train
            # pipeline already holds that dataset's worker guard
            dataset=(self.validation_dataset
                     if self.validation_dataset is not self.dataset
                     else None),
            shard=self.validation_dataset.process_shard_index())
        try:
            with trace.span("validation",
                            host_sync="per-batch metric eval"):
                for batch in val_iter:
                    data, labels = to_jax_batch(batch)
                    out = apply_fn(params, mstate, data)
                    count += data.shape[0]
                    for i, m in enumerate(self.validation_methods):
                        r = m(out, labels)
                        results[i] = r if results[i] is None \
                            else results[i] + r
        finally:
            val_iter.close()
        if jax.process_count() > 1:
            # each process validated its own shard; reduce to the global
            # result on every host (reference DistriValidator's driver
            # reduce). Safe as a collective: the trigger is a
            # deterministic function of the shared driver state
            from bigdl_tpu.optim.validation import aggregate_results
            from bigdl_tpu.parallel.collective import \
                process_allgather_pyobj
            results = aggregate_results(results)
            count = sum(process_allgather_pyobj(count))  # global records
        elapsed = time.perf_counter() - t0
        logger.info(f"validate model throughput is "
                    f"{count / max(elapsed, 1e-9):.2f} records/second")
        for m, r in zip(self.validation_methods, results):
            logger.info(f"{m!r} is {r!r}")
        if self.val_summary is not None:
            step = int(driver_state.get("neval", 0))
            for m, r in zip(self.validation_methods, results):
                self.val_summary.add_scalar(repr(m),
                                            float(r.result()[0]), step)
            self.val_summary.add_scalar(
                "ValidationThroughput",
                count / max(elapsed, 1e-9), step)
        return dict(zip([repr(m) for m in self.validation_methods], results))

    @staticmethod
    def _host_rng_snapshot() -> bytes:
        """Pickled host-RNG state. Captured at each training-iterator
        (re)creation: mid-epoch resume restores THIS state and replays the
        consumed batches, so the pipeline's random-augmentation draws land
        exactly where the uninterrupted run's did (restoring the
        checkpoint-time state would double-consume the replayed draws)."""
        import pickle
        from bigdl_tpu.utils.random import RandomGenerator
        return pickle.dumps(RandomGenerator.RNG()._rng.bit_generator.state)

    # -- async checkpoint writer lifecycle (bigdl_tpu/elastic/) --
    def _ckpt_writer_get(self):
        if self._ckpt_writer is None:
            from bigdl_tpu.elastic.checkpoint_writer import CheckpointWriter
            self._ckpt_writer = CheckpointWriter(name=type(self).__name__)
        return self._ckpt_writer

    def _ckpt_barrier(self):
        """Wait out every in-flight save (epoch end: the boundary
        shuffle and a new epoch's dispatch must not stack snapshots
        behind a slow filesystem)."""
        if self._ckpt_writer is not None:
            self._ckpt_writer.barrier()

    def _ckpt_shutdown(self, *, raise_errors: bool):
        """Drain + stop the writer and publish the save-overhead receipt
        (``self.checkpoint_receipt``). ``raise_errors=False`` is the
        already-failing path: a background save error must not mask the
        original exception."""
        w, self._ckpt_writer = self._ckpt_writer, None
        if w is None:
            return
        try:
            w.close()
        except Exception:
            if raise_errors:
                self.checkpoint_receipt = w.receipt()
                raise
            logger.warning("async checkpoint writer shutdown failed "
                           "(training already unwinding)", exc_info=True)
        self.checkpoint_receipt = w.receipt()

    def _snapshot_module(self, host_params, host_mstate):
        """Detached module snapshot for the background writer: deep-copy
        the TOPOLOGY only (all runtime arrays unbound during the copy —
        cloning device gradients would mean per-leaf transfers), then
        bind the already-on-host param/state trees onto the clone. The
        snapshot shares no mutable state with the training loop."""
        from bigdl_tpu.utils.file import _strip_runtime
        model = self.model
        saved = []

        def unbind(m):
            saved.append((m, m.params, m.state, m.grad_params, m._rng))
            m.params = m.state = m.grad_params = m._rng = None
            for child in getattr(m, "modules", []):
                unbind(child)

        unbind(model)
        try:
            snap = model.clone_module()
        finally:
            for m, p, s, g, r in saved:
                m.params, m.state, m.grad_params, m._rng = p, s, g, r
        _strip_runtime(snap)
        snap.params = host_params
        snap.state = host_mstate
        if host_params is not None:
            snap.sync(host_params, host_mstate)
        return snap

    def _checkpoint(self, driver_state, opt_state=None, rng=None,
                    record_count=0, batches_this_epoch=0,
                    epoch_start_host_rng: bytes | None = None, *,
                    fire: bool | None = None):
        """Save the WHOLE training state on trigger (reference
        DistriOptimizer.scala:319-341 saves the full state Table): driver
        counters + optimizer state (momentum/accumulators) + device rng +
        data-pipeline position + host-rng state, so a resumed run is the
        run that was stopped. ``fire``: pre-evaluated trigger decision.

        Elastic rendering (bigdl_tpu/elastic/, docs/ELASTICITY.md): the
        critical path pays ONE packed ``jax.device_get`` over every
        device leaf — mandatory either way, the next step's donated
        buffers must not be rewritten under a pending readback — and
        serialization runs on the background writer (``checkpoint_async``,
        default). Write order is model → state → manifest: the manifest
        is the commit point ``latest_checkpoint`` trusts, so a crash at
        any point never exposes a torn snapshot."""
        if fire is None:
            if self.checkpoint_trigger is None or \
                    self.checkpoint_path is None:
                return
            fire = self.checkpoint_trigger(driver_state)
        if not fire:
            return
        from bigdl_tpu.elastic.checkpoint_writer import snapshot_to_host
        from bigdl_tpu.elastic.manifest import (build_manifest,
                                                manifest_name,
                                                write_manifest)
        from bigdl_tpu.utils import file as _file
        neval = driver_state["neval"]
        suffix = "" if self.is_overwrite else f".{neval}"
        path = self.checkpoint_path
        t0 = time.perf_counter()
        host_params, host_mstate, host_opt, host_rng = snapshot_to_host(
            (self.model.params, self.model.state, opt_state, rng))
        module = self._snapshot_module(host_params, host_mstate)
        full_state = dict(driver_state)
        full_state["record_count"] = record_count
        full_state["batches_this_epoch"] = batches_this_epoch
        if host_opt is not None:
            full_state["opt_state"] = host_opt
        if host_rng is not None:
            full_state["rng"] = np.asarray(host_rng)
        # opaque bytes: the nested state dict (strings/ints/arrays) must
        # round-trip exactly, not through the array-flattening save path
        full_state["host_rng_state"] = (epoch_start_host_rng
                                        if epoch_start_host_rng is not None
                                        else self._host_rng_snapshot())
        # prefetch-era position state: the worker's read-ahead may have
        # advanced the LIVE state past the consumer (it can start the
        # next pass while the loop is still mid-epoch), so the loops
        # snapshot at pipeline creation and the snapshot is advanced by
        # the CONSUMER's progress — unconsumed prefetched batches fold
        # back into the saved position (dataset/prefetch.py)
        pos = self._epoch_position_state
        if pos is not None and batches_this_epoch > 0:
            pos = self.dataset.advance_position_state(pos)
        if pos is None:
            pos = self.dataset.get_position_state()
        if pos is not None:
            full_state["data_position"] = pos
        if self._pad_stage is not None and self._pad_stage.full_size:
            # the learned full batch shape: a resume whose first replayed
            # batch is the short one must still pad to the original size
            full_state["pad_full_size"] = int(self._pad_stage.full_size)
        # the saved mesh descriptor: resume redistributes onto whatever
        # mesh the new process initializes (elastic/redistribute.py)
        from bigdl_tpu.elastic.manifest import mesh_layout
        layout = mesh_layout(self._ckpt_mesh)
        if layout is not None:
            full_state["mesh_layout"] = layout
        manifest = build_manifest(
            neval=neval, epoch=int(driver_state["epoch"]),
            model_file=f"model{suffix}", state_file=f"state{suffix}",
            params=host_params, opt_state=host_opt, mesh=layout)
        model_path = f"{path}/model{suffix}"
        state_path = f"{path}/state{suffix}"
        manifest_path = f"{path}/{manifest_name(suffix)}"

        keep = None if self.is_overwrite else self.checkpoint_keep

        def write_job():
            _file.save_module(module, model_path, overwrite=True,
                              prepared=True)
            _file.save(full_state, state_path, overwrite=True)
            write_manifest(manifest, manifest_path)  # commit point
            if keep is not None:
                # retention GC strictly after the commit, on the single
                # writer thread — never concurrent with a write, and a
                # sweep failure must not fail the checkpoint
                from bigdl_tpu.elastic.manifest import sweep_checkpoints
                try:
                    sweep_checkpoints(path, keep)
                except Exception:
                    logger.warning("checkpoint GC failed for %s", path,
                                   exc_info=True)

        handoff_s = time.perf_counter() - t0
        if self.checkpoint_async:
            self._ckpt_writer_get().submit(
                write_job, label=f"neval={neval}", handoff_s=handoff_s)
            self.metrics.record("checkpoint handoff time", handoff_s)
            logger.info(f"Save model to {model_path} (async)")
        else:
            write_job()
            self.metrics.record("checkpoint handoff time",
                                time.perf_counter() - t0)
            logger.info(f"Save model to {model_path}")

    def set_profiler(self, trace_dir: str, start_iteration: int = 10,
                     num_iterations: int = 5):
        """Capture a ``jax.profiler`` trace of iterations
        [start, start+num) into ``trace_dir`` (SURVEY §7 step 7 — the
        XLA-native replacement for the reference's per-module
        forwardTime/backwardTime inspection; open with TensorBoard or
        Perfetto)."""
        self.profile_dir = trace_dir
        self.profile_start = start_iteration
        self.profile_iters = num_iterations
        return self

    def _profile_hook(self, neval: int):
        if self.profile_dir is None:
            return
        if not self._profiling and self.profile_iters > 0 and \
                self.profile_start <= neval < self.profile_start + \
                self.profile_iters:
            jax.profiler.start_trace(self.profile_dir)
            self._profiling = True
        elif self._profiling and neval >= self.profile_start + \
                self.profile_iters:
            jax.profiler.stop_trace()
            self._profiling = False
            logger.info("profiler trace written to %s", self.profile_dir)

    def _stop_profiler(self):
        if self._profiling:
            jax.profiler.stop_trace()
            self._profiling = False

    def _fires(self, driver_state) -> tuple[bool, bool]:
        """Evaluate the validation and checkpoint triggers EXACTLY once per
        iteration (a stateful user trigger must not be consumed twice) and
        return (fire_validation, fire_checkpoint)."""
        fire_val = (self.validation_trigger is not None
                    and self.validation_dataset is not None
                    and self.validation_trigger(driver_state))
        fire_ckpt = (self.checkpoint_trigger is not None
                     and self.checkpoint_path is not None
                     and self.checkpoint_trigger(driver_state))
        return fire_val, fire_ckpt

    # -- async dispatch (docs/PERFORMANCE.md) --
    def _loss_sync_reason(self) -> str | None:
        """Which configured trigger (if any) reads the loss and therefore
        forces a readback every iteration — the stopping decision must
        see the true per-step value, so the dispatch window collapses to
        lockstep."""
        for what, t in (("end_when", self.end_when),
                        ("validation trigger", self.validation_trigger),
                        ("checkpoint trigger", self.checkpoint_trigger)):
            if t is not None and "loss" in getattr(t, "requires",
                                                   frozenset()):
                return f"{what} {t!r} reads loss"
        return None

    def _dispatch_window(self) -> tuple[int, str | None]:
        """Effective in-flight window for this run: ``max_in_flight``
        unless a loss-reading trigger forces lockstep."""
        reason = self._loss_sync_reason()
        if reason is not None:
            if self.max_in_flight > 1:
                logger.info(
                    "async dispatch disabled (%s) — draining loss every "
                    "iteration to preserve exact stopping semantics",
                    reason)
            return 1, reason
        return self.max_in_flight, None

    def _emit_step(self, e: dict, loss: float) -> None:
        """Emit one drained step's log line + observability records,
        stamped with the step's ORIGINAL counters (the drain may run up
        to ``max_in_flight`` iterations later). The f-string is only
        built when INFO is live — this runs once per iteration."""
        if logger.isEnabledFor(logging.INFO):
            logger.info(
                self._header(e["epoch"], e["count"], e["epoch_size"],
                             e["neval"], e["wallclock"])
                + f" loss is {loss:.6f}, iteration time is "
                f"{e['step_time']:.4f}s, host input time is "
                f"{e['data_time']:.4f}s, device step time is "
                f"{e['device_time']:.4f}s, throughput is "
                f"{e['n'] / max(e['step_time'], 1e-9):.2f} records/second")
        self._record_step(e["neval"], loss, e["n"], e["step_time"],
                          e["data_time"], e["device_time"])

    def _drain_pending(self, pending: list, driver_state: dict,
                       reason: str) -> None:
        """Drain the in-flight window: ONE packed ``jax.device_get`` for
        every pending loss (the sanctioned batched readback — the only
        host<-device sync in the steady-state loop), then emit each
        step's deferred log line / summary scalars under its original
        ``neval``. The readback wait cannot be attributed to a single
        step once dispatch runs ahead, so it is amortized evenly across
        the window (window-amortized device time, docs/PERFORMANCE.md).
        """
        if not pending:
            return
        depth = len(pending)
        self.metrics.set("dispatch depth", depth)
        t0 = time.perf_counter()
        with trace.span("loss drain", host_sync="packed loss readback",
                        depth=depth, reason=reason):
            losses = jax.device_get([e["loss"] for e in pending])
        share = (time.perf_counter() - t0) / depth
        for e, lv in zip(pending, losses):
            loss = float(lv)
            e["device_time"] += share
            e["step_time"] += share
            self._emit_step(e, loss)
            driver_state["loss"] = loss
        pending.clear()

    def _resume(self, optim, params):
        """Rebuild (opt_state, rng, count_this_epoch, batches_to_skip) from
        ``self.state`` — full-fidelity when the state came from a round-2
        checkpoint, best-effort (the reference's epoch/neval semantics)
        otherwise."""
        from bigdl_tpu.utils.random import RandomGenerator
        opt_state = optim.init_state(params)
        saved = self.state.get("opt_state")
        if saved is not None:
            opt_state = jax.tree.map(jnp.asarray, dict(saved))
        elif int(self.state.get("neval", 1)) > 1:
            # legacy states carry no optimizer state — at least restore the
            # LR-schedule counter so decay doesn't restart
            opt_state["neval"] = jnp.asarray(
                int(self.state["neval"]) - 1, jnp.int32)
        saved_rng = self.state.get("rng")
        rng = (jnp.asarray(saved_rng) if saved_rng is not None
               else jax.random.PRNGKey(int(self.state.get("seed", 0))))
        host_state = self.state.get("host_rng_state")
        if host_state is not None:
            import pickle
            if not isinstance(host_state, bytes):
                host_state = np.asarray(host_state).item()
            RandomGenerator.RNG()._rng.bit_generator.state = \
                pickle.loads(host_state)
        count = int(self.state.get("record_count", 0))
        skip = int(self.state.get("batches_this_epoch", 0))
        pos = self.state.get("data_position")
        if pos is not None:
            self.dataset.set_position_state(pos, mid_pass=skip > 0)
        self._init_pad_stage()
        return opt_state, rng, count, skip

    # -- overlapped input pipeline (dataset/prefetch.py) --
    def _init_pad_stage(self):
        """Per-run partial-batch pad stage; the checkpoint carries the
        learned full batch size so a resume whose first replayed batch
        is the short one still pads to the original shape."""
        if not self.pad_partial_batches:
            self._pad_stage = None
            return
        if jax.process_count() > 1:
            raise ValueError(
                "pad_partial_batches is single-controller only: each "
                "process pads its own block of the global batch, so the "
                "in-step validity mask (arange < valid) cannot describe "
                "the multi-host row layout — pad per-process batches in "
                "the dataset pipeline instead")
        from bigdl_tpu.dataset.prefetch import PadPartialBatches
        saved = int(self.state.get("pad_full_size", 0))
        self._pad_stage = PadPartialBatches(saved or None)

    def _open_train_pipeline(self, place, *, skip: int = 0,
                             consumed: int = 0, records_scale: int = 1):
        """Build one epoch's input pipeline: raw dataset iterator ->
        optional partial-batch padding -> device placement, overlapped
        on a prefetch worker at ``prefetch_depth`` >= 1 (synchronous at
        0). The worker is EPOCH-BOUNDED (``max_records``) so its pull
        sequence — and therefore every host-RNG draw and pass
        transition — is exactly the synchronous loop's; the position
        state is snapshotted here, before the fast-forward pulls, for
        :meth:`_checkpoint`. MUST be close()d before
        ``dataset.shuffle()`` (thread-safety contract,
        dataset/prefetch.py)."""
        from bigdl_tpu.dataset.prefetch import open_input_pipeline
        self._epoch_position_state = self.dataset.get_position_state()
        raw = self.dataset.data(train=True)
        for _ in range(skip):   # fast-forward to the resume point
            next(raw)
        pad = self._pad_stage
        if pad is not None and place is not None:
            def stage(b, _pad=pad, _place=place):
                return _place(_pad(b))
        else:
            stage = pad if place is None else place
        max_records = None
        if self.prefetch_depth > 0:
            max_records = max(int(self.dataset.size()) - int(consumed), 0)
        return open_input_pipeline(raw, depth=self.prefetch_depth,
                                   stage=stage, max_records=max_records,
                                   records_scale=records_scale,
                                   name="train", dataset=self.dataset,
                                   shard=self.dataset.process_shard_index())


class LocalOptimizer(Optimizer):
    """Single-host training loop (reference optim/LocalOptimizer.scala)."""

    def _optimize_impl(self):
        model, criterion, optim = self.model, self.criterion, \
            self.optim_method
        if self.pipeline_stages > 1:
            raise ValueError(
                "pipeline_stages needs a device mesh to shard stages "
                "over — construct the optimizer with mesh= (or a "
                "sharded dataset) so the distributed path runs, with a "
                "'pipe' axis of that size")
        if self.shard_weight_update or self.wire_codec is not None:
            logger.info(
                "sharded update / wire codec configured, but the local "
                "optimizer is one program with no collectives — inert "
                "(DistriOptimizer runs the sharded path)")
        model.materialize()
        model.training()
        params, mstate = model.params, model.state
        # resume support (reference: epoch/neval live in the state Table,
        # DistriOptimizer.scala:80-81; full opt_state/rng/data-position
        # restore when the state came from a checkpoint)
        driver_state = {"epoch": int(self.state.get("epoch", 1)),
                        "neval": int(self.state.get("neval", 1)),
                        "is_epoch_end": False, "loss": float("inf")}
        opt_state, rng, count_this_epoch, batches_to_skip = \
            self._resume(optim, params)

        use_mask = self._pad_stage is not None
        masked = None
        if use_mask:
            from bigdl_tpu.nn.criterion import MaskedCriterion
            masked = MaskedCriterion(criterion)

        # the step program is assembled from the memory knobs: the
        # (possibly remat-wrapped) forward and the microbatched
        # gradient-accumulation scan (optim/remat.py,
        # optim/accumulation.py); policy "none" + k=1 is EXACTLY the
        # plain step
        from bigdl_tpu.optim.accumulation import make_train_step
        from bigdl_tpu.optim.remat import remat_forward
        train_step = make_train_step(
            fwd=remat_forward(model, self.remat_policy),
            criterion=criterion, masked=masked,
            input_transform=self.input_transform,
            grad_clip=self.grad_clip, update_fn=optim.update,
            num_microbatches=self.grad_accumulation,
            aux_loss=self._aux_loss_fn())

        # explicit lower -> compile -> cache step construction
        # (tuning/aot_cache.py): executables are built per batch
        # signature OUTSIDE the hot loop's dispatch path, optionally
        # loaded from the persistent AOT cache (set_aot_cache /
        # $BIGDL_TPU_AOT_CACHE_DIR) so a restarting worker skips XLA;
        # per-call signature counting keeps compile_watch's
        # calls/compiles/storm accounting identical to the old
        # implicit-jit path
        from bigdl_tpu.tuning.aot_cache import StepCompiler
        step_pipeline = StepCompiler(
            jax.jit(train_step, donate_argnums=(0, 1, 2)),
            name="local_train_step", cache=self._aot_cache() or False,
            donate_argnums=(0, 1, 2), extra=self._step_key_extra(),
            count_calls=True)

        def eval_apply(params, mstate, data):
            if self.input_transform is not None:
                data = self.input_transform(data)
            out, _ = model.apply(params, mstate, data, training=False)
            return out

        jit_eval = jax.jit(eval_apply)

        def place(b):
            # runs on the prefetch worker (depth >= 1): host->device
            # transfer overlaps the in-flight device steps
            if isinstance(b.data, jax.Array):
                return b   # a user pipeline already placed it
            from bigdl_tpu.dataset.sample import MiniBatch
            return MiniBatch(jnp.asarray(b.data), jnp.asarray(b.labels),
                             valid=b.valid)

        epoch_start_host_rng = self._host_rng_snapshot()
        epoch_size = self.dataset.size()
        batches_this_epoch = batches_to_skip
        pipeline = self._open_train_pipeline(place, skip=batches_to_skip,
                                             consumed=count_this_epoch)
        window, lockstep = self._dispatch_window()
        pending: list[dict] = []
        wallclock_start = time.perf_counter()

        try:
            while self.end_when is None or not self.end_when(driver_state):
                driver_state["is_epoch_end"] = False
                self._profile_hook(driver_state["neval"])
                t0 = time.perf_counter()
                with trace.span("input wait"):
                    # at depth >= 1 this is a queue pop — assembly and
                    # placement happened on the worker ("input produce")
                    batch = next(pipeline)
                t1 = time.perf_counter()
                data_time = t1 - t0
                data, labels = batch.data, batch.labels
                n = int(batch.valid if batch.valid is not None
                        else data.shape[0])
                rng, step_rng = jax.random.split(rng)
                step_args = (params, mstate, opt_state, step_rng, data,
                             labels,
                             jnp.asarray(driver_state["epoch"], jnp.int32))
                if use_mask:
                    step_args += (jnp.asarray(n, jnp.int32),)
                # quick dispatch key: only the batch varies between
                # iterations (params/opt state keep their avals through
                # donation) — two leaves to hash, full signature only on
                # a miss inside the pipeline
                quick = compile_watch.signature_of((data, labels))
                compiled, _ = step_pipeline.get(quick, step_args)
                with trace.span("device step"):
                    # dispatch only — loss stays on device; the packed
                    # readback happens at drain time (docs/PERFORMANCE.md)
                    params, mstate, opt_state, loss = compiled(*step_args)
                t2 = time.perf_counter()
                self._telemetry_step()
                count_this_epoch += n
                batches_this_epoch += 1
                pending.append({"epoch": driver_state["epoch"],
                                "count": count_this_epoch,
                                "epoch_size": epoch_size,
                                "neval": driver_state["neval"],
                                "wallclock": time.perf_counter()
                                - wallclock_start,
                                "loss": loss, "n": n,
                                "step_time": t2 - t0,
                                "data_time": data_time,
                                "device_time": t2 - t1})
                if len(pending) >= window:
                    self._drain_pending(pending, driver_state,
                                        lockstep or "window full")
                driver_state["neval"] += 1
                if count_this_epoch >= epoch_size:
                    self._drain_pending(pending, driver_state, "epoch end")
                    self._emit_input_wait_fraction(driver_state["neval"])
                    # epoch-end checkpoint barrier: pending async saves
                    # commit before the next epoch dispatches (bounds
                    # queued snapshots; surfaces background save errors
                    # at the boundary)
                    self._ckpt_barrier()
                    driver_state["epoch"] += 1
                    driver_state["is_epoch_end"] = True
                    count_this_epoch = 0
                    batches_this_epoch = 0
                    # drain + join the worker BEFORE shuffle() touches
                    # the order it iterates (thread-safety contract,
                    # dataset/prefetch.py), then restart it on the fresh
                    # epoch's iterator
                    pipeline.close()
                    self.dataset.shuffle()
                    epoch_start_host_rng = self._host_rng_snapshot()
                    pipeline = self._open_train_pipeline(place)
                fire_val, fire_ckpt = self._fires(driver_state)
                if fire_val or fire_ckpt:
                    # validation/checkpoint read host-visible state: flush
                    # the window first, then publish params (syncing the
                    # module tree every iteration is pure host overhead)
                    self._drain_pending(pending, driver_state,
                                        "validation/checkpoint trigger")
                    model.sync(params, mstate)
                self._validate(jit_eval, params, mstate, driver_state,
                               fire=fire_val)
                self._checkpoint(driver_state, opt_state, rng,
                                 count_this_epoch, batches_this_epoch,
                                 epoch_start_host_rng, fire=fire_ckpt)
        finally:
            pipeline.close()

        self._drain_pending(pending, driver_state, "training end")
        # exit barrier: every handed-off checkpoint is committed (and any
        # background save error raised) before optimize() returns
        self._ckpt_shutdown(raise_errors=True)
        self._stop_profiler()
        model.sync(params, mstate)
        model.evaluate()
        return model

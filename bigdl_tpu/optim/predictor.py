"""Batch predictor — the inference-path API.

Reference parity: DLClassifier (org/apache/spark/ml/DLClassifier.scala:
36-138) batches DataFrame rows into a reused input tensor, forwards the
ModelBroadcast-shipped model, and argmaxes into a prediction column; plus
``modelPredictRDD`` (python/api/PythonBigDL.scala:211-260).

TPU-native: one jitted eval fn; the ModelBroadcast role is params
replication over the mesh (pad the final batch to the mesh multiple, trim
after). Sources can be a pre-batched dataset, an iterable of Samples, or a
single ndarray.
"""
from __future__ import annotations

from typing import Iterator

import jax
import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import MiniBatch, Sample

__all__ = ["Predictor"]


class Predictor:
    """(reference ml/DLClassifier.scala:36-138)"""

    def __init__(self, model, batch_size: int = 32, mesh=None):
        self.model = model
        self.batch_size = batch_size
        self.mesh = mesh
        model.materialize()
        model.evaluate()

        if mesh is not None:
            from bigdl_tpu.parallel.engine import data_sharding, replicated
            repl = replicated(mesh)
            self._batch_shard = data_sharding(mesh)
            self._n_shards = int(np.prod(mesh.devices.shape))
            self._params = jax.device_put(model.params, repl)
            self._mstate = jax.device_put(model.state, repl)
            self._eval = jax.jit(
                self._apply,
                in_shardings=(repl, repl, self._batch_shard),
                out_shardings=self._batch_shard)
        else:
            self._batch_shard = None
            self._n_shards = 1
            self._params, self._mstate = model.params, model.state
            self._eval = jax.jit(self._apply)

    def _apply(self, params, mstate, data):
        out, _ = self.model.apply(params, mstate, data, training=False)
        return out

    # -- batching ---------------------------------------------------------
    def _batches(self, source) -> Iterator[np.ndarray]:
        if isinstance(source, AbstractDataSet):
            for b in source.data(train=False):
                yield np.asarray(b.data if isinstance(b, MiniBatch) else b)
            return
        if isinstance(source, np.ndarray) or hasattr(source, "__array__"):
            arr = np.asarray(source)
            for i in range(0, arr.shape[0], self.batch_size):
                yield arr[i:i + self.batch_size]
            return
        buf = []
        for item in source:
            if isinstance(item, MiniBatch):
                yield np.asarray(item.data)
                continue
            feat = item.feature if isinstance(item, Sample) else item
            buf.append(np.asarray(feat))
            if len(buf) == self.batch_size:
                yield np.stack(buf)
                buf = []
        if buf:
            yield np.stack(buf)

    def _forward(self, data: np.ndarray) -> np.ndarray:
        n = data.shape[0]
        pad = (-n) % self._n_shards
        if pad:
            data = np.concatenate([data, np.repeat(data[-1:], pad, axis=0)])
        if self._batch_shard is not None:
            data = jax.device_put(data, self._batch_shard)
        out = self._eval(self._params, self._mstate, data)
        return np.asarray(out)[:n]

    # -- public API -------------------------------------------------------
    def predict(self, source) -> np.ndarray:
        """Forward every record; returns the stacked outputs (reference
        modelPredictRDD role)."""
        outs = [self._forward(d) for d in self._batches(source)]
        if not outs:
            return np.zeros((0,), np.float32)
        return np.concatenate(outs, axis=0)

    def predict_class(self, source) -> np.ndarray:
        """Argmax over the last dim, 1-based to match ClassNLL labels
        (reference DLClassifier argmax->prediction column, :103-125)."""
        out = self.predict(source)
        if out.size == 0:
            return np.zeros((0,), np.int64)
        return np.argmax(out, axis=-1) + 1

"""Named training metrics.

Reference parity: optim/Metrics.scala:24-117 — named counters in local /
aggregate / per-node-distributed scopes, dumped via ``summary()``. The Spark
accumulator scopes collapse to host-side counters here (one process per
host in the TPU runtime); the reference's cross-node accumulator scope
(Metrics.scala:24-27 accumulableCollection) is provided by
:meth:`Metrics.aggregated`, a collective merge of every process's counters
over the jax.distributed job — call it (on all hosts) when the operator
needs the all-hosts view instead of the local one.

Honest phase naming: the reference's per-iteration phases ("get weights
average", "computing time for each node", "aggregate gradient time") don't
exist under XLA — weight sync, compute, and the gradient allreduce fuse
into one compiled step. The optimizers therefore record what IS measurable:

- ``host input time``  — next(batch) + host->device sharding
- ``device step time`` — dispatch + execution of the jitted train step

``record()`` keeps the per-iteration series so ``stats()``/``summary()``
report the distribution (mean/p50/p95/max) — the SPMD replacement for the
reference's straggler diagnostics (per-replica time table,
DistriOptimizer.scala:249-277): lockstep collectives can't drop members,
but a fat tail in step time is still the signal an operator looks for.

Registry shim: every ``set``/``add``/``record`` also lands in a
``bigdl_tpu.observability`` metric registry (the process-wide default
unless ``registry=`` is given) — ``set`` -> Gauge, ``add`` ->
``*_total`` Counter, ``record`` -> Histogram — so optimizer metrics
export through the same Prometheus/JSON surface as serving and bench
metrics. The per-name series stays HERE (exact percentiles +
:meth:`aggregated`'s cross-host merge need raw values, which fixed
histogram buckets deliberately discard); the registry carries the
operator-facing view.
"""
from __future__ import annotations

import threading
from collections import defaultdict, deque

from bigdl_tpu.observability.registry import default_registry, sanitize_name

__all__ = ["Metrics"]


class Metrics:
    def __init__(self, keep: int = 4096, registry=None,
                 namespace: str = "bigdl"):
        self._lock = threading.Lock()
        self._scalars: dict[str, float] = {}
        self._counts: dict[str, int] = defaultdict(int)
        self._distributed: dict[str, list] = {}
        self._series: dict[str, deque] = {}
        self._keep = keep
        self._ns = namespace
        self._registry = registry if registry is not None \
            else default_registry()

    def _mirror(self, kind: str, name: str, value: float) -> None:
        """Best-effort registry export; observability must never break
        training (e.g. a display name that sanitizes onto a metric
        already registered as a different kind)."""
        mname = f"{self._ns}_{sanitize_name(name)}"
        try:
            if kind == "gauge":
                self._registry.gauge(
                    mname, f"Metrics scalar '{name}'").set(value)
            elif kind == "counter":
                if value >= 0:
                    self._registry.counter(
                        f"{mname}_total",
                        f"Metrics accumulator '{name}'").inc(value)
            else:
                self._registry.histogram(
                    mname, f"Metrics series '{name}'").observe(value)
        except ValueError:
            pass

    def set(self, name: str, value: float, parallel: int = 1):
        """(reference Metrics.set)"""
        with self._lock:
            self._scalars[name] = float(value) / parallel
        self._mirror("gauge", name, float(value) / parallel)

    def add(self, name: str, value: float):
        """(reference Metrics.add on accumulators)"""
        with self._lock:
            self._scalars[name] = self._scalars.get(name, 0.0) + float(value)
            self._counts[name] += 1
        self._mirror("counter", name, float(value))

    def set_distributed(self, name: str, values):
        with self._lock:
            self._distributed[name] = list(values)

    def get(self, name: str) -> float:
        return self._scalars.get(name, 0.0)

    def record(self, name: str, value: float):
        """Append to the per-iteration series for ``name`` (bounded to the
        last ``keep`` observations)."""
        with self._lock:
            if name not in self._series:
                self._series[name] = deque(maxlen=self._keep)
            self._series[name].append(float(value))
        self._mirror("histogram", name, float(value))

    def stats(self, name: str) -> dict:
        """Distribution of a recorded series: n/mean/p50/p95/max."""
        import numpy as np
        with self._lock:
            vals = np.asarray(self._series.get(name, ()), dtype=float)
        if vals.size == 0:
            return {"n": 0, "mean": 0.0, "p50": 0.0, "p95": 0.0, "max": 0.0}
        return {"n": int(vals.size), "mean": float(vals.mean()),
                "p50": float(np.percentile(vals, 50)),
                "p95": float(np.percentile(vals, 95)),
                "max": float(vals.max())}

    def _snapshot(self) -> dict:
        with self._lock:
            return {"scalars": dict(self._scalars),
                    "counts": dict(self._counts),
                    "distributed": {k: list(v)
                                    for k, v in self._distributed.items()},
                    "series": {k: list(v) for k, v in self._series.items()}}

    def _merge_snapshot(self, snap: dict) -> None:
        with self._lock:
            for k, v in snap["scalars"].items():
                if snap["counts"].get(k, 0) > 0:    # add()-accumulated: sum
                    self._scalars[k] = self._scalars.get(k, 0.0) + v
                    self._counts[k] += snap["counts"][k]
                elif k not in self._scalars:        # set(): first host wins
                    self._scalars[k] = v
            for k, v in snap["distributed"].items():
                self._distributed.setdefault(k, []).extend(v)
            for k, v in snap["series"].items():
                if k not in self._series:
                    self._series[k] = deque(maxlen=self._keep)
                self._series[k].extend(v)

    def aggregated(self) -> "Metrics":
        """Cross-host merge (reference Metrics distributed scope,
        Metrics.scala:24-27,96-108): every process contributes its
        counters and the returned Metrics reflects ALL hosts —
        add()-accumulators sum, series concatenate in process order,
        set() scalars take the first host's value. COLLECTIVE: every
        process of the jax.distributed job must call this at the same
        point (it rides a device all-gather). Single-process it is a
        plain copy. The originals are left untouched."""
        import jax

        from bigdl_tpu.parallel.collective import process_allgather_pyobj

        out = Metrics(keep=self._keep * max(1, jax.process_count()))
        for snap in process_allgather_pyobj(self._snapshot()):
            out._merge_snapshot(snap)
        return out

    def summary(self, unit: str = "s", scale: float = 1.0) -> str:
        """(reference Metrics.summary, Metrics.scala:96-108) — scalar means
        plus distribution lines for recorded series."""
        with self._lock:
            series_names = sorted(self._series)
            lines = ["========== Metrics Summary =========="]
            for k in sorted(self._scalars):
                # add()-accumulated metrics report their mean, matching the
                # reference's aggregated-accumulator summary
                # (Metrics.scala:96-108)
                denom = max(self._counts.get(k, 0), 1) * scale
                lines.append(f"{k} : {self._scalars[k] / denom} {unit}")
            for k in sorted(self._distributed):
                lines.append(f"{k} : {self._distributed[k]}")
        for k in series_names:
            s = self.stats(k)
            lines.append(
                f"{k} : mean={s['mean']:.6f}{unit} p50={s['p50']:.6f}{unit} "
                f"p95={s['p95']:.6f}{unit} max={s['max']:.6f}{unit} "
                f"(n={s['n']})")
        lines.append("=====================================")
        return "\n".join(lines)

"""Named training metrics.

Reference parity: optim/Metrics.scala:24-117 — named counters in local /
aggregate / per-node-distributed scopes, dumped via ``summary()``. The Spark
accumulator scopes collapse to host-side counters here (one process per
host in the TPU runtime); per-phase timings are set each iteration by the
optimizers, mirroring DistriOptimizer.scala:113-117.
"""
from __future__ import annotations

import threading
from collections import defaultdict

__all__ = ["Metrics"]


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._scalars: dict[str, float] = {}
        self._counts: dict[str, int] = defaultdict(int)
        self._distributed: dict[str, list] = {}

    def set(self, name: str, value: float, parallel: int = 1):
        """(reference Metrics.set)"""
        with self._lock:
            self._scalars[name] = float(value) / parallel

    def add(self, name: str, value: float):
        """(reference Metrics.add on accumulators)"""
        with self._lock:
            self._scalars[name] = self._scalars.get(name, 0.0) + float(value)
            self._counts[name] += 1

    def set_distributed(self, name: str, values):
        with self._lock:
            self._distributed[name] = list(values)

    def get(self, name: str) -> float:
        return self._scalars.get(name, 0.0)

    def summary(self, unit: str = "s", scale: float = 1.0) -> str:
        """(reference Metrics.summary, Metrics.scala:96-108)"""
        with self._lock:
            lines = ["========== Metrics Summary =========="]
            for k in sorted(self._scalars):
                # add()-accumulated metrics report their mean, matching the
                # reference's aggregated-accumulator summary
                # (Metrics.scala:96-108)
                denom = max(self._counts.get(k, 0), 1) * scale
                lines.append(f"{k} : {self._scalars[k] / denom} {unit}")
            for k in sorted(self._distributed):
                lines.append(f"{k} : {self._distributed[k]}")
            lines.append("=====================================")
            return "\n".join(lines)

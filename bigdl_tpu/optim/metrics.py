"""Named training metrics.

Reference parity: optim/Metrics.scala:24-117 — named counters in local /
aggregate / per-node-distributed scopes, dumped via ``summary()``. The Spark
accumulator scopes collapse to host-side counters here (one process per
host in the TPU runtime).

Honest phase naming: the reference's per-iteration phases ("get weights
average", "computing time for each node", "aggregate gradient time") don't
exist under XLA — weight sync, compute, and the gradient allreduce fuse
into one compiled step. The optimizers therefore record what IS measurable:

- ``host input time``  — next(batch) + host->device sharding
- ``device step time`` — dispatch + execution of the jitted train step

``record()`` keeps the per-iteration series so ``stats()``/``summary()``
report the distribution (mean/p50/p95/max) — the SPMD replacement for the
reference's straggler diagnostics (per-replica time table,
DistriOptimizer.scala:249-277): lockstep collectives can't drop members,
but a fat tail in step time is still the signal an operator looks for.
"""
from __future__ import annotations

import threading
from collections import defaultdict, deque

__all__ = ["Metrics"]


class Metrics:
    def __init__(self, keep: int = 4096):
        self._lock = threading.Lock()
        self._scalars: dict[str, float] = {}
        self._counts: dict[str, int] = defaultdict(int)
        self._distributed: dict[str, list] = {}
        self._series: dict[str, deque] = {}
        self._keep = keep

    def set(self, name: str, value: float, parallel: int = 1):
        """(reference Metrics.set)"""
        with self._lock:
            self._scalars[name] = float(value) / parallel

    def add(self, name: str, value: float):
        """(reference Metrics.add on accumulators)"""
        with self._lock:
            self._scalars[name] = self._scalars.get(name, 0.0) + float(value)
            self._counts[name] += 1

    def set_distributed(self, name: str, values):
        with self._lock:
            self._distributed[name] = list(values)

    def get(self, name: str) -> float:
        return self._scalars.get(name, 0.0)

    def record(self, name: str, value: float):
        """Append to the per-iteration series for ``name`` (bounded to the
        last ``keep`` observations)."""
        with self._lock:
            if name not in self._series:
                self._series[name] = deque(maxlen=self._keep)
            self._series[name].append(float(value))

    def stats(self, name: str) -> dict:
        """Distribution of a recorded series: n/mean/p50/p95/max."""
        import numpy as np
        with self._lock:
            vals = np.asarray(self._series.get(name, ()), dtype=float)
        if vals.size == 0:
            return {"n": 0, "mean": 0.0, "p50": 0.0, "p95": 0.0, "max": 0.0}
        return {"n": int(vals.size), "mean": float(vals.mean()),
                "p50": float(np.percentile(vals, 50)),
                "p95": float(np.percentile(vals, 95)),
                "max": float(vals.max())}

    def summary(self, unit: str = "s", scale: float = 1.0) -> str:
        """(reference Metrics.summary, Metrics.scala:96-108) — scalar means
        plus distribution lines for recorded series."""
        with self._lock:
            series_names = sorted(self._series)
            lines = ["========== Metrics Summary =========="]
            for k in sorted(self._scalars):
                # add()-accumulated metrics report their mean, matching the
                # reference's aggregated-accumulator summary
                # (Metrics.scala:96-108)
                denom = max(self._counts.get(k, 0), 1) * scale
                lines.append(f"{k} : {self._scalars[k] / denom} {unit}")
            for k in sorted(self._distributed):
                lines.append(f"{k} : {self._distributed[k]}")
        for k in series_names:
            s = self.stats(k)
            lines.append(
                f"{k} : mean={s['mean']:.6f}{unit} p50={s['p50']:.6f}{unit} "
                f"p95={s['p95']:.6f}{unit} max={s['max']:.6f}{unit} "
                f"(n={s['n']})")
        lines.append("=====================================")
        return "\n".join(lines)

"""SGD with the reference's full learning-rate-schedule surface.

Reference parity: optim/SGD.scala:25-209 — weight decay, momentum/dampening/
nesterov, and pluggable ``LearningRateSchedule``: Default (1/(1+n*decay)),
Step, EpochStep, EpochDecay, Poly, EpochSchedule with Regime list.

TPU-first: the update is a pure pytree function compiled into the train step
(so it fuses with the gradient allreduce); the schedule is a scalar function
of the (traced) iteration/epoch counters.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import jax
import jax.numpy as jnp

from bigdl_tpu.optim.optim_method import OptimMethod, _tree_unzip

__all__ = ["SGD", "Default", "Step", "EpochStep", "EpochDecay", "Poly",
           "Regime", "EpochSchedule", "Warmup", "CosineAnnealing"]


# ---------------------------------------------------------------------------
# Learning-rate schedules (reference SGD.scala:127-209). Each maps the
# training counters to the current LR; ``neval`` is the iteration count and
# ``epoch`` the 1-based epoch, both jit-traceable scalars.
# ---------------------------------------------------------------------------

class LearningRateSchedule:
    def __call__(self, lr, neval, epoch):
        raise NotImplementedError

    def effective(self) -> "LearningRateSchedule":
        """The schedule whose TYPE governs SGD's special cases (Default
        decay, EpochSchedule weight-decay regimes). Wrappers (Warmup)
        override to return their inner schedule, so nesting never
        silently disables the introspection."""
        return self


@dataclass
class Default(LearningRateSchedule):
    """clr = lr / (1 + neval * decay) (reference SGD.Default)."""

    def __call__(self, lr, neval, epoch):
        return lr  # decay applied by SGD via learning_rate_decay


@dataclass
class Step(LearningRateSchedule):
    """clr = lr * gamma^floor(neval / step_size) (reference SGD.Step)."""
    step_size: int
    gamma: float

    def __call__(self, lr, neval, epoch):
        return lr * jnp.power(self.gamma,
                              jnp.floor(neval / self.step_size))


@dataclass
class EpochStep(LearningRateSchedule):
    """clr = lr * gamma^floor((epoch-1) / step_size)
    (reference SGD.EpochStep)."""
    step_size: int
    gamma: float

    def __call__(self, lr, neval, epoch):
        return lr * jnp.power(self.gamma,
                              jnp.floor((epoch - 1) / self.step_size))


@dataclass
class EpochDecay(LearningRateSchedule):
    """clr = lr * 0.1^decay_fn(epoch) (reference SGD.EpochDecay)."""
    decay_fn: Callable

    def __call__(self, lr, neval, epoch):
        return lr * jnp.power(0.1, self.decay_fn(epoch))


@dataclass
class Poly(LearningRateSchedule):
    """clr = lr * (1 - neval/max_iteration)^power (reference SGD.Poly —
    the Inception-v1 recipe schedule, inception/Train.scala:70-88)."""
    power: float
    max_iteration: int

    def __call__(self, lr, neval, epoch):
        frac = jnp.minimum(neval / self.max_iteration, 1.0)
        return lr * jnp.power(1.0 - frac, self.power)


@dataclass
class Warmup(LearningRateSchedule):
    """Linear warmup over ``warmup_iterations`` then hand off to
    ``after`` (transformer-era extension; the reference's schedules are
    all decay-only)."""
    warmup_iterations: int
    after: LearningRateSchedule = field(default_factory=Default)

    def __call__(self, lr, neval, epoch):
        frac = jnp.minimum((neval + 1) / self.warmup_iterations, 1.0)
        post = self.after(lr, neval - self.warmup_iterations, epoch)
        return jnp.where(neval < self.warmup_iterations, lr * frac, post)

    def effective(self):
        return self.after.effective()


@dataclass
class CosineAnnealing(LearningRateSchedule):
    """clr = min_lr + (lr - min_lr) * (1 + cos(pi * t/T)) / 2
    (SGDR-style single cycle; transformer-era extension)."""
    max_iteration: int
    min_lr: float = 0.0

    def __call__(self, lr, neval, epoch):
        frac = jnp.minimum(jnp.maximum(neval, 0) / self.max_iteration, 1.0)
        return self.min_lr + (lr - self.min_lr) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * frac))


@dataclass
class Regime:
    """[start_epoch, end_epoch] -> config overrides
    (reference SGD.Regime)."""
    start_epoch: int
    end_epoch: int
    config: dict = field(default_factory=dict)


@dataclass
class EpochSchedule(LearningRateSchedule):
    """Piecewise-per-epoch config regimes (reference SGD.EpochSchedule)."""
    regimes: list

    def __call__(self, lr, neval, epoch):
        out = lr
        for r in self.regimes:
            in_regime = (epoch >= r.start_epoch) & (epoch <= r.end_epoch)
            out = jnp.where(in_regime, r.config.get("learningRate", lr), out)
        return out

    def weight_decay(self, base_wd, epoch):
        out = base_wd
        for r in self.regimes:
            in_regime = (epoch >= r.start_epoch) & (epoch <= r.end_epoch)
            out = jnp.where(in_regime, r.config.get("weightDecay", base_wd),
                            out)
        return out


class SGD(OptimMethod):
    """(reference optim/SGD.scala:25-125)"""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_decay: float = 0.0,
                 weight_decay: float = 0.0,
                 momentum: float = 0.0,
                 dampening: float | None = None,
                 nesterov: bool = False,
                 learning_rate_schedule: LearningRateSchedule | None = None,
                 learning_rates=None, weight_decays=None):
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.weight_decay = weight_decay
        self.momentum = momentum
        self.dampening = momentum if dampening is None else dampening
        self.nesterov = nesterov
        self.schedule = learning_rate_schedule or Default()
        self.learning_rates = learning_rates      # per-param lr pytree/vector
        self.weight_decays = weight_decays
        if nesterov and (momentum <= 0 or self.dampening != 0):
            raise ValueError(
                "Nesterov momentum requires momentum > 0 and dampening = 0 "
                "(reference SGD.scala requirement)")

    def init_state(self, params):
        state = {"neval": jnp.zeros((), jnp.int32),
                 "epoch": jnp.ones((), jnp.int32)}
        if self.momentum > 0:
            state["velocity"] = jax.tree.map(jnp.zeros_like, params)
        return state

    def current_lr(self, state):
        lr = self.schedule(self.learning_rate, state["neval"],
                           state["epoch"])
        # Default's decay is applied here (it needs SGD's
        # learning_rate_decay knob) — including when Default is the
        # post-warmup schedule inside (possibly nested) Warmup
        inner = self.schedule.effective()
        if isinstance(inner, Default):
            neval = state["neval"]
            # subtract warmup iterations across EVERY Warmup layer so
            # nested Warmup(Warmup(Default)) decays from the true
            # post-warmup iteration count
            sched = self.schedule
            while isinstance(sched, Warmup):
                neval = neval - sched.warmup_iterations
                sched = sched.after
            neval = jnp.maximum(neval, 0)
            lr = lr / (1.0 + neval * self.learning_rate_decay)
        return lr

    # Tried and rejected (round 3): a flat-vector update (concatenate
    # every leaf, one fused kernel, split back) to kill the per-leaf
    # kernel-launch overhead the ResNet-50 trace showed (160 fusions,
    # 8.3 ms/step). Measured WORSE: ResNet-50 2334 -> 1195 img/s,
    # Inception 5069 -> 4200 — the concat/split breaks XLA's in-place
    # buffer donation, so the whole parameter+velocity set round-trips
    # through fresh buffers every step. The per-leaf tree.map form keeps
    # donation (XLA updates weights in place in HBM); its launch
    # overhead is the cheaper evil. Re-measure whole-model before
    # reintroducing any flattening here.

    _SMALL_LEAF = 16384   # elements; see _grouped_update below

    #: gate for the concatenated small-leaf update. DistriOptimizer sets
    #: this False when parameters or optimizer state are mesh-sharded
    #: (tensor parallelism, ZeRO-1): concatenating leaves with mixed
    #: NamedShardings and slicing the fused result back was measured to
    #: MISCOMPILE under GSPMD — every updated value came back multiplied
    #: by the data-axis size (reproduced on the 8-device CPU mesh; the
    #: per-leaf form is correct). Grouping is only a kernel-launch
    #: optimization, and under sharded layouts the concat would force a
    #: resharding round-trip anyway, so skipping it there is also the
    #: faster choice.
    group_small_leaves: bool = True

    def update(self, grads, params, state):
        clr = self.current_lr(state)
        wd = self.weight_decay
        eff = self.schedule.effective()
        if isinstance(eff, EpochSchedule):
            wd = eff.weight_decay(wd, state["epoch"])
        mom, damp = self.momentum, self.dampening

        def upd(g, p, v, lr_scale=None, wd_leaf=None):
            wd_eff = wd if wd_leaf is None else wd_leaf
            if wd_eff is not None:
                g = g + wd_eff * p
            if mom > 0:
                v_new = mom * v + (1.0 - damp) * g
                if self.nesterov:
                    g = g + mom * v_new
                else:
                    g = v_new
            else:
                v_new = v
            step = clr * g
            if lr_scale is not None:
                step = step * lr_scale
            return p - step, v_new

        velocity_in = state.get("velocity") if mom > 0 else None
        if self.learning_rates is not None or \
                self.weight_decays is not None:
            # per-param hyperparameter pytrees (reference SGD.scala
            # learningRates/weightDecays tensors, tree-shaped here)
            new_params, velocity = self._per_param_update(
                upd, grads, params, velocity_in)
        else:
            grouped = self._grouped_update(upd, grads, params,
                                           velocity_in)
            if grouped is not None:
                new_params, velocity = grouped
            elif mom > 0:
                flat = jax.tree.map(upd, grads, params,
                                    state["velocity"])
                new_params, velocity = _tree_unzip(flat, 2)
            else:
                new_params = jax.tree.map(
                    lambda g, p: upd(g, p, None)[0], grads, params)
                velocity = None
        new_state = dict(state, neval=state["neval"] + 1)
        if mom > 0:
            new_state["velocity"] = velocity
        return new_params, new_state

    def _per_param_update(self, upd, grads, params, velocity):
        """Leafwise update with per-parameter learning-rate scales and/or
        weight decays — each a pytree matching ``params`` (or a scalar,
        broadcast to every leaf)."""
        leaves_p, treedef = jax.tree.flatten(params)

        def hyper_leaves(spec):
            if spec is None:
                return [None] * len(leaves_p)
            spec_def = jax.tree.structure(spec)
            if spec_def == treedef:
                return jax.tree.leaves(spec)
            if spec_def != jax.tree.structure(0):   # not a true leaf
                # a partially-specified / misspelled tree would otherwise
                # broadcast as if it were a scalar and fail far away
                raise ValueError(
                    "SGD: per-parameter hyper tree does not match params "
                    f"structure — params {treedef}, got {spec_def}")
            return [spec] * len(leaves_p)      # scalar broadcast

        leaves_g = self._matched_leaves(grads, treedef)
        leaves_v = (self._matched_leaves(velocity, treedef)
                    if velocity is not None else [None] * len(leaves_p))
        lrs = hyper_leaves(self.learning_rates)
        wds = hyper_leaves(self.weight_decays)
        out = [upd(g, p, v, lr, w) for g, p, v, lr, w
               in zip(leaves_g, leaves_p, leaves_v, lrs, wds)]
        new_p = jax.tree.unflatten(treedef, [o[0] for o in out])
        new_v = (jax.tree.unflatten(treedef, [o[1] for o in out])
                 if velocity is not None else None)
        return new_p, new_v

    @staticmethod
    def _matched_leaves(tree, treedef):
        got = jax.tree.structure(tree)
        if got != treedef:
            raise ValueError(
                f"SGD.update: tree structure mismatch — params "
                f"{treedef}, got {got}")
        return jax.tree.leaves(tree)

    def _grouped_update(self, upd, grads, params, velocity):
        """Per-leaf updates compile to one tiny kernel per parameter
        (ResNet-50: 157 fusions, ~47 us launch overhead each, 8 ms/step
        — round-3 trace). SMALL f32 leaves (BN gammas/betas, biases)
        are updated on one concatenated vector instead; big leaves keep
        the per-leaf form so XLA's in-place buffer donation still covers
        ~99% of the parameter bytes (the all-leaf flat form was measured
        2x slower — see the rejection note above). Disabled entirely
        (``group_small_leaves=False``) when leaves carry mesh shardings —
        see the attribute note."""
        if not self.group_small_leaves:
            return None
        leaves_p, treedef = jax.tree.flatten(params)
        # full structure check (tree.map would raise; flatten-order
        # pairing on a mismatched tree would silently mis-assign)
        leaves_g = self._matched_leaves(grads, treedef)
        leaves_v = (self._matched_leaves(velocity, treedef)
                    if velocity is not None else [None] * len(leaves_p))
        small = [i for i, l in enumerate(leaves_p)
                 if l.size <= self._SMALL_LEAF and l.ndim >= 1
                 and l.dtype == jnp.float32
                 and leaves_g[i].dtype == jnp.float32]
        if len(small) < 16:          # not worth a concat kernel
            return None
        small_set = set(small)
        out_p = list(leaves_p)
        out_v = list(leaves_v)
        for i in range(len(leaves_p)):
            if i not in small_set:
                out_p[i], out_v[i] = upd(leaves_g[i], leaves_p[i],
                                         leaves_v[i])
        cat = lambda leaves: jnp.concatenate(
            [leaves[i].reshape(-1) for i in small])
        new_ps, new_vs = upd(cat(leaves_g), cat(leaves_p),
                             cat(leaves_v) if velocity is not None
                             else None)
        off = 0
        for i in small:
            n = leaves_p[i].size
            out_p[i] = jax.lax.dynamic_slice_in_dim(
                new_ps, off, n).reshape(leaves_p[i].shape)
            if velocity is not None:
                out_v[i] = jax.lax.dynamic_slice_in_dim(
                    new_vs, off, n).reshape(leaves_p[i].shape)
            off += n
        return (jax.tree.unflatten(treedef, out_p),
                jax.tree.unflatten(treedef, out_v)
                if velocity is not None else None)

    def get_hyper_parameter(self, state=None):
        if state is None:
            return f"Current learning rate is {self.learning_rate}"
        return f"Current learning rate is {float(self.current_lr(state))}"

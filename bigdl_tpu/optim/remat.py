"""Remat policy registry: named activation-rematerialization policies
applied to the model forward at step-construction time (ISSUE 10
tentpole).

Training MFU is activation-memory-bound before it is compute-bound: the
per-chip batch is capped by the residuals XLA saves between forward and
backward, not by MXU throughput. ``jax.checkpoint`` trades those HBM
bytes for recompute FLOPs (usually idle in memory-bound steps); this
module names the useful points on that tradeoff so they are a training
KNOB (``remat_policy=`` on both optimizers, a ``tune()`` axis, an AOT
cache-key component) rather than a per-model wrapper decision:

- ``"none"``         — save every residual (the default; zero recompute)
- ``"dots_saveable"``— save matmul/conv outputs, recompute elementwise
                       chains (cheap recompute, moderate savings)
- ``"per_block"``    — checkpoint each top-level block of a
                       ``Sequential`` stack (transformer / inception):
                       only block-boundary activations are saved, one
                       block's interior is recomputed at a time — the
                       selective policy deep stacks want
- ``"nothing_saveable"`` — save only the checkpointed region's inputs;
                       maximum savings, one full forward of recompute

Policies are SEMANTICALLY INVISIBLE: the recomputed forward is the same
program, so outputs AND gradients are bit-identical to the unwrapped
model (tests/test_remat.py pins it). Only memory and recompute move.

Static receipt: :func:`saved_residual_bytes` counts the bytes the
backward actually saves via abstract ``jax.vjp`` partial-eval — no
compile, no execution, backend-independent. This is deliberately NOT
the compiled executable's ``memory_analysis()``: the CPU backend CSEs
rematerialized subgraphs away (no HBM pressure to respect), so only the
jaxpr-level accounting shows the policy effect everywhere; the TPU
buffer assignment honors it. ``train_memory_probe`` (bench
``train_peak_hbm_bytes`` row) reports both.
"""
from __future__ import annotations

import logging

logger = logging.getLogger("bigdl_tpu.optim")

__all__ = ["REMAT_POLICIES", "known_remat_policies", "check_remat_policy",
           "remat_forward", "saved_residual_bytes", "train_memory_probe"]

#: policy name -> jax.checkpoint policy factory (None = the whole-forward
#: default policy, "save nothing"); "none"/"per_block" are handled
#: structurally in remat_forward.
REMAT_POLICIES = ("none", "dots_saveable", "per_block", "nothing_saveable")


def known_remat_policies() -> tuple:
    return REMAT_POLICIES


def check_remat_policy(name):
    """Validate (and normalize) a policy name; None means "none"."""
    name = "none" if name is None else str(name)
    if name not in REMAT_POLICIES:
        raise ValueError(f"unknown remat policy {name!r} "
                         f"(known: {list(REMAT_POLICIES)})")
    return name


def _checkpoint_policy(name):
    import jax
    if name == "dots_saveable":
        return jax.checkpoint_policies.dots_saveable
    if name == "nothing_saveable":
        return jax.checkpoint_policies.nothing_saveable
    raise AssertionError(name)


def remat_forward(model, policy):
    """The model forward the train step should differentiate through:
    ``fwd(params, state, x, training=..., rng=...) -> (y, new_state)``.

    ``"none"`` returns ``model.apply`` untouched — the plain step is
    EXACTLY the pre-remat construction (golden fixtures unaffected).
    ``"per_block"`` checkpoints each top-level child of a ``Sequential``
    with the child-index rng fold mirrored from ``Sequential.apply`` so
    dropout draws land identically; non-Sequential models degrade to a
    whole-forward checkpoint (logged).
    """
    import jax

    from bigdl_tpu.nn.containers import Sequential
    from bigdl_tpu.nn.module import _fold

    policy = check_remat_policy(policy)
    if policy == "none":
        return model.apply

    if policy == "per_block":
        if not isinstance(model, Sequential):
            logger.info(
                "remat_policy='per_block' on a %s (not a Sequential "
                "stack) — checkpointing the whole forward instead",
                type(model).__name__)

            def whole(params, state, x, *, training=False, rng=None):
                def inner(p, s, xx, r):
                    return model.apply(p, s, xx, training=training, rng=r)
                return jax.checkpoint(inner)(params, state, x, rng)

            return whole

        def per_block(params, state, x, *, training=False, rng=None):
            # mirrors Sequential.apply exactly (same rng folds, same
            # state tree) with each block its own checkpoint region:
            # only the residual stream at block boundaries is saved
            new_state = {}
            for i, m in enumerate(model.modules):
                def block(p, s, xx, r, _m=m):
                    return _m.apply(p, s, xx, training=training, rng=r)

                x, s = jax.checkpoint(block)(params[str(i)], state[str(i)],
                                             x, _fold(rng, i))
                new_state[str(i)] = s
            return x, new_state

        return per_block

    chk_policy = _checkpoint_policy(policy)

    def whole_forward(params, state, x, *, training=False, rng=None):
        def inner(p, s, xx, r):
            return model.apply(p, s, xx, training=training, rng=r)

        return jax.checkpoint(inner, policy=chk_policy)(params, state, x,
                                                        rng)

    return whole_forward


def saved_residual_bytes(loss_fn, *args) -> int:
    """Bytes of residuals the backward of ``loss_fn(*args)`` saves,
    counted by abstract ``jax.vjp`` partial-eval (the returned vjp
    closure is a pytree whose leaves ARE the saved residuals). Pure
    shape evaluation: nothing compiles, nothing executes — this is the
    activation-memory term a remat policy controls, measured the same
    on every backend."""
    import numpy as np

    import jax

    def capture(*a):
        _, vjp = jax.vjp(loss_fn, *a)
        return vjp

    shapes = jax.eval_shape(capture, *args)
    return int(sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
                   for l in jax.tree.leaves(shapes)
                   if hasattr(l, "shape")))


def _tree_bytes(tree) -> int:
    import numpy as np

    import jax
    return int(sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
                   for l in jax.tree.leaves(tree)
                   if hasattr(l, "shape")))


def train_memory_probe(*, d_model: int = 256, num_layers: int = 4,
                       seq: int = 1024, batch: int = 8,
                       vocab: int = 8192,
                       policies=REMAT_POLICIES,
                       accum_k: int = 4,
                       compile_accum: bool = True) -> dict:
    """Static peak-HBM accounting for the transformer train step across
    remat policies at FIXED effective batch (the bench
    ``train_peak_hbm_bytes`` row; tests call it in-process at tiny
    geometry).

    Per policy: ``saved_residual_bytes`` of the step's loss (abstract —
    fast even at bench geometry) plus the persistent-state term (params,
    grads, optimizer state) that does not move with the policy; modeled
    ``peak_hbm_bytes = persistent + residuals``. ``reduction`` is
    peak(none) / peak(nothing_saveable) — the acceptance number.

    ``compile_accum=True`` additionally compiles the k=1 and k=accum_k
    steps and reports executable ``memory_analysis`` temp bytes: the
    microbatched scan bounds activation liveness in the BUFFER
    ASSIGNMENT itself, so this one shows on the CPU backend too (remat
    does not — CPU CSEs the recompute; see module docstring)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.observability.compile_watch import executable_stats
    from bigdl_tpu.optim.accumulation import split_microbatches
    from bigdl_tpu.optim.sgd import SGD

    model = TransformerLM(vocab, d_model=d_model,
                          num_heads=max(d_model // 64, 1),
                          num_layers=num_layers, max_len=seq,
                          with_log_softmax=False)
    model.materialize(jax.random.PRNGKey(0))
    model.training()
    criterion = nn.CrossEntropyCriterion()
    optim = SGD(learning_rate=0.01, momentum=0.9)
    params, mstate = model.params, model.state
    opt_state = optim.init_state(params)
    host = np.random.default_rng(0)
    data = jnp.asarray(host.integers(1, vocab + 1, size=(batch, seq)))
    labels = jnp.asarray(host.integers(1, vocab + 1, size=(batch, seq)))

    persistent = (_tree_bytes(params) * 2          # params + grads
                  + _tree_bytes(opt_state))
    resid, peak = {}, {}
    for pol in policies:
        fwd = remat_forward(model, pol)

        def loss_fn(p, _fwd=fwd):
            y, _ = _fwd(p, mstate, data, training=True,
                        rng=jax.random.PRNGKey(1))
            return criterion.apply(y, labels)

        rb = saved_residual_bytes(loss_fn, params)
        resid[pol] = rb
        peak[pol] = persistent + rb

    out = {
        "geometry": f"transformer d{d_model} L{num_layers} B{batch} "
                    f"S{seq} V{vocab}",
        "persistent_bytes": persistent,
        "saved_residual_bytes": resid,
        "peak_hbm_bytes": peak,
        "reduction": (peak["none"] / peak["nothing_saveable"]
                      if "none" in peak and "nothing_saveable" in peak
                      else None),
        "residual_reduction": {
            p: (resid["none"] / r if r else None)
            for p, r in resid.items()} if "none" in resid else {},
    }

    if compile_accum:
        def step(params, mstate, opt_state, rng, data, labels, k):
            def mb_loss(p, d, l):
                y, s = model.apply(p, mstate, d, training=True, rng=rng)
                return criterion.apply(y, l), s

            if k == 1:
                (loss, s2), g = jax.value_and_grad(
                    mb_loss, has_aux=True)(params, data, labels)
            else:
                ds = split_microbatches(data, k)
                ls = split_microbatches(labels, k)

                def body(carry, xs):
                    d, l = xs
                    (lv, _), g = jax.value_and_grad(
                        mb_loss, has_aux=True)(params, d, l)
                    gacc, lacc = carry
                    return (jax.tree.map(jnp.add, gacc, g),
                            lacc + lv), None

                zero = jax.tree.map(jnp.zeros_like, params)
                (g, lsum), _ = jax.lax.scan(body,
                                            (zero, jnp.zeros(())),
                                            (ds, ls))
                g = jax.tree.map(lambda a: a / k, g)
                loss = lsum / k
            p2, o2 = optim.update(g, params, opt_state)
            return p2, o2, loss

        accum = {}
        for k in (1, int(accum_k)):
            from functools import partial
            c = jax.jit(partial(step, k=k),
                        donate_argnums=(0, 1, 2)).lower(
                params, mstate, opt_state, jax.random.PRNGKey(0),
                data, labels).compile()
            accum[str(k)] = executable_stats(c)
        out["accum_executable_stats"] = accum
        t1 = accum["1"].get("temp_bytes")
        tk = accum[str(int(accum_k))].get("temp_bytes")
        out["accum_temp_reduction"] = (t1 / tk if t1 and tk else None)
        out["accum_k"] = int(accum_k)
    return out

"""Validation methods and addable results.

Reference parity: optim/ValidationMethod.scala:26-219 — Top1Accuracy,
Top5Accuracy, Loss; results are monoids combined across cores/partitions
(here: across batches/devices).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["ValidationResult", "AccuracyResult", "LossResult",
           "ValidationMethod", "Top1Accuracy", "Top5Accuracy", "Loss",
           "aggregate_results"]


def aggregate_results(results):
    """Monoid-reduce a list of per-process ValidationResults across the
    jax.distributed job (reference DistriValidator.scala:29-80 — each
    executor evaluates its partition, the driver reduces): every host
    returns the all-hosts sums. COLLECTIVE (all processes must call at
    the same point); single-process it returns ``results`` unchanged.
    ``None`` entries (a host whose shard was empty) are skipped."""
    from bigdl_tpu.parallel.collective import process_allgather_pyobj
    per_host = process_allgather_pyobj(list(results))
    merged = list(per_host[0])
    for host_results in per_host[1:]:
        for i, r in enumerate(host_results):
            if r is None:
                continue
            merged[i] = r if merged[i] is None else merged[i] + r
    return merged


class ValidationResult:
    def result(self) -> tuple[float, int]:
        raise NotImplementedError

    def __add__(self, other):
        raise NotImplementedError


class AccuracyResult(ValidationResult):
    """(correct, count) monoid (reference ValidationMethod.scala:29-56)."""

    def __init__(self, correct: int, count: int):
        self.correct, self.count = int(correct), int(count)

    def result(self):
        return (self.correct / max(self.count, 1), self.count)

    def __add__(self, other):
        return AccuracyResult(self.correct + other.correct,
                              self.count + other.count)

    def __eq__(self, other):
        return (self.correct, self.count) == (other.correct, other.count)

    def __repr__(self):
        acc, cnt = self.result()
        return f"Accuracy(correct: {self.correct}, count: {cnt}, " \
               f"accuracy: {acc})"


class LossResult(ValidationResult):
    def __init__(self, loss: float, count: int):
        self.loss, self.count = float(loss), int(count)

    def result(self):
        return (self.loss / max(self.count, 1), self.count)

    def __add__(self, other):
        return LossResult(self.loss + other.loss, self.count + other.count)

    def __repr__(self):
        mean, cnt = self.result()
        return f"Loss(loss: {self.loss}, count: {cnt}, mean: {mean})"


class ValidationMethod:
    """output x target -> ValidationResult."""

    def __call__(self, output, target) -> ValidationResult:
        raise NotImplementedError


class Top1Accuracy(ValidationMethod):
    """(reference ValidationMethod.scala:90-123; targets 1-based)"""

    def __call__(self, output, target):
        out = np.asarray(output)
        t = np.asarray(target).reshape(-1).astype(np.int64)
        if out.ndim == 1:
            out = out[None]
        pred = out.argmax(axis=-1) + 1
        return AccuracyResult(int((pred == t).sum()), t.shape[0])

    def __repr__(self):
        return "Top1Accuracy"


class Top5Accuracy(ValidationMethod):
    """(reference ValidationMethod.scala:125-163)"""

    def __call__(self, output, target):
        out = np.asarray(output)
        t = np.asarray(target).reshape(-1).astype(np.int64)
        if out.ndim == 1:
            out = out[None]
        top5 = np.argsort(-out, axis=-1)[:, :5] + 1
        correct = int((top5 == t[:, None]).any(axis=1).sum())
        return AccuracyResult(correct, t.shape[0])

    def __repr__(self):
        return "Top5Accuracy"


class Loss(ValidationMethod):
    """Mean criterion loss (reference ValidationMethod.scala:207-219)."""

    def __init__(self, criterion):
        self.criterion = criterion

    def __call__(self, output, target):
        l = float(self.criterion.apply(jnp.asarray(output),
                                       jnp.asarray(target)))
        n = np.asarray(output).shape[0]
        return LossResult(l * n, n)

    def __repr__(self):
        return "Loss"

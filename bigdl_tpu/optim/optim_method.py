"""Optimization-method protocol + Adagrad + LBFGS.

Reference parity: OptimMethod (optim/OptimMethod.scala:25-70 — Torch-style
``optimize(feval, x, config, state)``), Adagrad (optim/Adagrad.scala),
LBFGS + lswolfe LineSearch (optim/LBFGS.scala, LineSearch.scala).

TPU-first protocol: ``init_state(params)`` + pure ``update(grads, params,
state) -> (params, state)`` over pytrees, compiled into the train step. The
Torch-style ``optimize(feval, x)`` facade is kept for LBFGS-style full-batch
use and reference-API parity.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["OptimMethod", "Adagrad", "Adam", "AdamW", "LBFGS"]


def _tree_unzip(tree, n):
    """Split a tree.map result whose leaves are n-tuples into n trees.
    Assumes no structural tuple nodes in params pytrees (all dict-keyed
    here) — the one place that assumption lives."""
    leaf = lambda x: isinstance(x, tuple)
    return tuple(jax.tree.map(lambda x: x[i], tree, is_leaf=leaf)
                 for i in range(n))


class OptimMethod:
    """Base optimizer."""

    def init_state(self, params) -> dict:
        return {"neval": jnp.zeros((), jnp.int32),
                "epoch": jnp.ones((), jnp.int32)}

    def update(self, grads, params, state):
        """Pure pytree update; returns (new_params, new_state)."""
        raise NotImplementedError

    # Torch-style facade (reference OptimMethod.optimize)
    def optimize(self, feval, x, state=None):
        """``feval(x) -> (loss, grad)`` on a flat vector or pytree;
        performs ONE step; returns (new_x, [loss], state)."""
        if state is None:
            state = self.init_state(x)
        loss, grad = feval(x)
        new_x, state = self.update(grad, x, state)
        return new_x, [loss], state

    def clone(self):
        import copy
        return copy.deepcopy(self)


class Adagrad(OptimMethod):
    """(reference optim/Adagrad.scala — standard accumulator, eps 1e-10)"""

    def __init__(self, learning_rate: float = 1e-3,
                 learning_rate_decay: float = 0.0,
                 weight_decay: float = 0.0):
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.weight_decay = weight_decay

    def init_state(self, params):
        return {"neval": jnp.zeros((), jnp.int32),
                "epoch": jnp.ones((), jnp.int32),
                "accum": jax.tree.map(jnp.zeros_like, params)}

    def update(self, grads, params, state):
        clr = self.learning_rate / (1.0 + state["neval"]
                                    * self.learning_rate_decay)

        def upd(g, p, a):
            if self.weight_decay > 0:
                g = g + self.weight_decay * p
            a_new = a + jnp.square(g)
            p_new = p - clr * g / (jnp.sqrt(a_new) + 1e-10)
            return p_new, a_new

        pairs = jax.tree.map(upd, grads, params, state["accum"])
        new_params, accum = _tree_unzip(pairs, 2)
        return new_params, dict(state, accum=accum,
                                neval=state["neval"] + 1)


class Adam(OptimMethod):
    """Adam (Kingma & Ba). Beyond the reference's 2016 menu (SGD /
    Adagrad / LBFGS) — carried as the TPU-era default a reference user
    switching to transformer-scale training expects. Torch-convention
    update (bias-corrected moments; ``weight_decay`` adds L2 to the
    gradient like torch.optim.Adam)."""

    def __init__(self, learning_rate: float = 1e-3, beta1: float = 0.9,
                 beta2: float = 0.999, eps: float = 1e-8,
                 weight_decay: float = 0.0,
                 learning_rate_schedule=None):
        self.learning_rate = learning_rate
        self.beta1, self.beta2, self.eps = beta1, beta2, eps
        self.weight_decay = weight_decay
        self.schedule = learning_rate_schedule

    decoupled = False   # AdamW flips this

    def init_state(self, params):
        return {"neval": jnp.zeros((), jnp.int32),
                "epoch": jnp.ones((), jnp.int32),
                "m": jax.tree.map(jnp.zeros_like, params),
                "v": jax.tree.map(jnp.zeros_like, params)}

    def update(self, grads, params, state):
        t = state["neval"] + 1
        b1, b2 = self.beta1, self.beta2
        c1 = 1.0 - b1 ** t.astype(jnp.float32)
        c2 = 1.0 - b2 ** t.astype(jnp.float32)
        lr = self.learning_rate
        if self.schedule is not None:
            lr = self.schedule(lr, state["neval"], state["epoch"])

        def upd(g, p, m, v):
            if self.weight_decay > 0 and not self.decoupled:
                g = g + self.weight_decay * p
            m_new = b1 * m + (1 - b1) * g
            v_new = b2 * v + (1 - b2) * jnp.square(g)
            step = lr * (m_new / c1) / (jnp.sqrt(v_new / c2) + self.eps)
            if self.weight_decay > 0 and self.decoupled:
                step = step + lr * self.weight_decay * p
            return p - step, m_new, v_new

        triples = jax.tree.map(upd, grads, params, state["m"], state["v"])
        new_params, m, v = _tree_unzip(triples, 3)
        return new_params, dict(state, m=m, v=v, neval=t)


class AdamW(Adam):
    """Adam with decoupled weight decay (Loshchilov & Hutter), matching
    torch.optim.AdamW's update."""

    decoupled = True

    def __init__(self, learning_rate: float = 1e-3, beta1: float = 0.9,
                 beta2: float = 0.999, eps: float = 1e-8,
                 weight_decay: float = 1e-2,
                 learning_rate_schedule=None):
        super().__init__(learning_rate, beta1, beta2, eps, weight_decay,
                         learning_rate_schedule)


class LBFGS(OptimMethod):
    """Limited-memory BFGS with optional Wolfe line search
    (reference optim/LBFGS.scala:25-286, LineSearch.scala lswolfe).

    Works on the flat parameter vector (the reference requires the
    flattened ``getParameters()`` view; here ``optimize`` accepts any
    pytree and flattens internally). Full-batch method: drive it through
    ``optimize(feval, x)``.
    """

    def __init__(self, max_iter: int = 20, max_eval: float | None = None,
                 tolerance_fun: float = 1e-5, tolerance_x: float = 1e-9,
                 n_correction: int = 100, learning_rate: float = 1.0,
                 line_search: bool = False):
        self.max_iter = max_iter
        self.max_eval = max_eval or max_iter * 1.25
        self.tolerance_fun = tolerance_fun
        self.tolerance_x = tolerance_x
        self.n_correction = n_correction
        self.learning_rate = learning_rate
        self.line_search = line_search

    def optimize(self, feval, x, state=None):
        from bigdl_tpu.tensor import flatten_params
        flat0, unravel = flatten_params(x)

        def f(v):
            loss, g = feval(unravel(v))
            gflat, _ = flatten_params(g)
            return jnp.asarray(loss), gflat

        fx, g = f(flat0)
        losses = [float(fx)]
        if float(jnp.max(jnp.abs(g))) <= self.tolerance_fun:
            return x, losses, state or {}

        xk = flat0
        s_list, y_list, ro_list = [], [], []
        H_diag = 1.0
        n_eval = 1
        g_prev, x_prev = g, xk

        for it in range(self.max_iter):
            # two-loop recursion
            q = -g
            alphas = []
            for s, y, ro in zip(reversed(s_list), reversed(y_list),
                                reversed(ro_list)):
                a = ro * jnp.dot(s, q)
                alphas.append(a)
                q = q - a * y
            q = q * H_diag
            for (s, y, ro), a in zip(zip(s_list, y_list, ro_list),
                                     reversed(alphas)):
                b = ro * jnp.dot(y, q)
                q = q + s * (a - b)
            d = q

            # the host loop needs two scalars before it can step
            # (descent check + first-iteration scale); read them in ONE
            # packed transfer instead of two blocking float() calls
            gtd_h, gsum_h = (
                float(v) for v in jax.device_get(
                    jnp.stack([jnp.dot(g, d), jnp.sum(jnp.abs(g))])))
            if gtd_h > -self.tolerance_x:
                break
            t = self.learning_rate if it > 0 else \
                min(1.0, 1.0 / gsum_h) * self.learning_rate

            if self.line_search:
                t, fx, g, n_ls = self._lswolfe(f, xk, fx, g, d, t)
                n_eval += n_ls
                xk = xk + t * d
            else:
                xk = xk + t * d
                fx_new, g_new = f(xk)
                n_eval += 1
                fx, g = fx_new, g_new

            s = xk - x_prev
            y = g - g_prev
            ys = jnp.dot(y, s)
            # post-step scalars (loss, curvature, grad inf-norm) ride
            # one packed transfer too: 2 device→host syncs per
            # iteration total, down from 5 scattered float() reads
            fx_h, ys_h, ginf_h = (
                float(v) for v in jax.device_get(
                    jnp.stack([jnp.asarray(fx), ys,
                               jnp.max(jnp.abs(g))])))
            losses.append(fx_h)
            if ys_h > 1e-10:
                if len(s_list) == self.n_correction:
                    s_list.pop(0)
                    y_list.pop(0)
                    ro_list.pop(0)
                s_list.append(s)
                y_list.append(y)
                ro_list.append(1.0 / ys)
                H_diag = ys / jnp.dot(y, y)
            x_prev, g_prev = xk, g

            if n_eval >= self.max_eval:
                break
            if ginf_h <= self.tolerance_fun:
                break
            if len(losses) > 1 and abs(losses[-1] - losses[-2]) \
                    < self.tolerance_fun:
                break

        return unravel(xk), losses, state or {}

    @staticmethod
    def _lswolfe(f, x, fx, g, d, t, c1=1e-4, c2=0.9, max_ls=25):
        """Backtracking Wolfe line search (reference LineSearch.lswolfe).

        Each probe reads exactly ONE packed (loss, directional-grad)
        scalar pair from the device — the search is host-driven, so
        per-probe syncs are unavoidable, but they need not be three."""
        fx0_h, gtd0_h = (
            float(v) for v in jax.device_get(
                jnp.stack([jnp.asarray(fx), jnp.dot(g, d)])))
        n_eval = 0
        lo, hi = 0.0, None
        for _ in range(max_ls):
            fx_t, g_t = f(x + t * d)
            n_eval += 1
            fx_h, gtd_h = (
                float(v) for v in jax.device_get(
                    jnp.stack([jnp.asarray(fx_t), jnp.dot(g_t, d)])))
            if fx_h > fx0_h + c1 * t * gtd0_h:
                hi = t
            elif abs(gtd_h) <= -c2 * gtd0_h:
                return t, fx_t, g_t, n_eval
            elif gtd_h < 0:
                lo = t
            else:
                hi = t
            t = (lo + hi) / 2.0 if hi is not None else t * 2.0
        fx_t, g_t = f(x + t * d)
        return t, fx_t, g_t, n_eval + 1

"""Fused Pallas TPU kernel for cross-map LRN (forward + analytic backward).

Why this kernel exists (the profile that justifies it, VERDICT round 1 #10):
an Inception-v1 train step at batch 128 spends ~6 ms / 5.2 GB of HBM traffic
in its two LRN layers even after an analytic ``custom_vjp`` on the XLA path —
`lax.reduce_window` materializes the f32 window-sum (308 MB at 192×56×56)
and the surrounding elementwise chain fuses poorly around it. This kernel
does the whole thing in one HBM pass per direction:

- forward:  read x (activation dtype), write y          — 2 tensors
- backward: read g and x, recompute the window sums in
  VMEM, write dx                                        — 3 tensors

vs. the XLA path's ~8 tensor-equivalents. All arithmetic is f32 in VMEM;
only the activation-precision tensors ever touch HBM.

Reference parity: nn/SpatialCrossMapLRN.scala (same y = x / (k +
alpha/size * sum_win x^2)^beta semantics); the hand-written backward mirrors
the reference's ``updateGradInput`` algebra rather than autodiff.

Layout: operates on (N, C, H*W) — channels on sublanes so the size-wide
window sum is a handful of sublane shifts, spatial positions on lanes.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from bigdl_tpu.ops import pow_neg_beta as _pow_neg_beta

__all__ = ["lrn", "lrn_supported"]

_LANE_TILE = 512  # spatial positions per program; 192ch f32 temps ≈ 1.5 MB


def _sublane(dtype) -> int:
    return 16 if jnp.dtype(dtype).itemsize == 2 else 8


def lrn_supported(x) -> bool:
    """Kernel constraints: TPU backend, NCHW with C a full sublane tile."""
    return (jax.default_backend() == "tpu" and x.ndim == 4
            and x.shape[1] % _sublane(x.dtype) == 0)


def _window_sum(v, size, adjoint=False):
    """Sum over a size-wide window along axis 0 (channels, sublanes).

    ``adjoint`` transposes the (asymmetric, for even sizes) padding —
    required for the backward sum over windows covering a position.
    """
    half = (size - 1) // 2
    lo, hi = (size - 1 - half, half) if adjoint else (half, size - 1 - half)
    c = v.shape[0]
    p = jnp.pad(v, ((lo, hi), (0, 0)))
    out = p[0:c]
    for d in range(1, size):
        out = out + p[d:d + c]
    return out


def _fwd_kernel(x_ref, y_ref, *, size, alpha, beta, k):
    x = x_ref[0].astype(jnp.float32)
    s = k + (alpha / size) * _window_sum(jnp.square(x), size)
    y_ref[0] = (x * _pow_neg_beta(s, beta)).astype(y_ref.dtype)


def _bwd_kernel(g_ref, x_ref, dx_ref, *, size, alpha, beta, k):
    # dx_i = g_i*s_i^-b - (2ab/n) * x_i * sum_win(g_j * x_j * s_j^-(b+1))
    g = g_ref[0].astype(jnp.float32)
    x = x_ref[0].astype(jnp.float32)
    s = k + (alpha / size) * _window_sum(jnp.square(x), size)
    sb = _pow_neg_beta(s, beta)
    acc = _window_sum(g * x * sb / s, size, adjoint=True)
    dx = g * sb - (2.0 * alpha * beta / size) * x * acc
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _call(kernel, args, n, c, hw, dtype, interpret):
    grid = (n, pl.cdiv(hw, _LANE_TILE))
    spec = pl.BlockSpec((1, c, _LANE_TILE), lambda i, t: (i, 0, t))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, c, hw), dtype),
        grid=grid,
        in_specs=[spec] * len(args),
        out_specs=spec,
        interpret=interpret,
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn(x, size=5, alpha=1.0, beta=0.75, k=1.0, interpret=False):
    """Cross-map LRN over NCHW via the fused Pallas kernel."""
    n, c, h, w = x.shape
    xf = x.reshape(n, c, h * w)
    kern = functools.partial(_fwd_kernel, size=size, alpha=alpha, beta=beta,
                             k=k)
    y = _call(kern, (xf,), n, c, h * w, x.dtype, interpret)
    return y.reshape(x.shape)


def _lrn_fwd(x, size, alpha, beta, k, interpret):
    return lrn(x, size, alpha, beta, k, interpret), x


def _lrn_bwd(size, alpha, beta, k, interpret, x, g):
    n, c, h, w = x.shape
    kern = functools.partial(_bwd_kernel, size=size, alpha=alpha, beta=beta,
                             k=k)
    dx = _call(kern, (g.reshape(n, c, h * w), x.reshape(n, c, h * w)),
               n, c, h * w, x.dtype, interpret)
    return (dx.reshape(x.shape),)


lrn.defvjp(_lrn_fwd, _lrn_bwd)

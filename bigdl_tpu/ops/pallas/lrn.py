"""Fused Pallas TPU kernel for cross-map LRN (forward + analytic backward).

Why this kernel exists (the profile that justifies it, VERDICT round 1 #10):
an Inception-v1 train step at batch 128 spends ~6 ms / 5.2 GB of HBM traffic
in its two LRN layers even after an analytic ``custom_vjp`` on the XLA path —
`lax.reduce_window` materializes the f32 window-sum (308 MB at 192×56×56)
and the surrounding elementwise chain fuses poorly around it. This kernel
does the whole thing in one HBM pass per direction:

- forward:  read x (activation dtype), write y          — 2 tensors
- backward: read g and x, recompute the window sums in
  VMEM, write dx                                        — 3 tensors

vs. the XLA path's ~8 tensor-equivalents. All arithmetic is f32 in VMEM;
only the activation-precision tensors ever touch HBM.

Reference parity: nn/SpatialCrossMapLRN.scala (same y = x / (k +
alpha/size * sum_win x^2)^beta semantics); the hand-written backward mirrors
the reference's ``updateGradInput`` algebra rather than autodiff.

Round-3 redesign (VERDICT r2 weak #1), two load-bearing decisions:

1. Layout: the kernel consumes a (H*W, C, N) VIEW of the NCHW
   activation. XLA's TPU backend lays conv activations out as
   ``{0,1,3,2}`` — N on lanes, C on sublanes, spatial major — so the
   transpose+reshape to (H*W, C, N) row-major is layout-preserving and
   folds to a bitcast, where the previous (N, C, H*W) form forced a
   physical relayout copy on BOTH sides of every kernel call
   (~3.3 GB/step at batch 256, measured in the round-3 HLO audit).
2. The channel-window sum is a banded (C, C) matmul on the MXU, not
   ``size`` sublane-shifted adds — sublane rotates across vreg
   boundaries serialize on the VPU (backward kernel measured 254 GB/s;
   the band form reaches HBM speed).

In-model effect on the Inception-v1 bench: 4316 -> 4993 img/s
(docs/PERF.md round-3 table has the per-change breakdown).
"""
from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from bigdl_tpu.ops import pow_neg_beta as _pow_neg_beta

__all__ = ["lrn", "lrn_supported"]

# spatial rows per program. Swept in-model on v5e batch 256 (round 3):
# shift-form kernel HT 2/4/8 -> 4627/4754/4633 img/s; band-matmul kernel
# HT 4/8 -> 4920/4993 img/s, HT>=16 fails to compile (f32 temps exceed
# VMEM at C=192, N=256). ``_pick_hw_tile`` scales the tile DOWN with
# C*N so bigger batches stay inside the same ~6 MB f32-temp budget the
# HT=8/C=192/N=256 winner used, instead of VMEM-crashing.
_HW_TILE = 8
_TEMP_BUDGET = 8 * 192 * 256 * 4    # bytes per f32 temp at the swept max


def _pick_hw_tile(c: int, n: int) -> int:
    # an autotuned winner for this (C, N, device kind) overrides the
    # static sweep (bigdl_tpu/tuning); illegal records fall through
    from bigdl_tpu.tuning.records import default_records
    cfg = default_records().lookup("lrn", {"c": c, "n": n})
    if cfg:
        try:
            ht = int(cfg["ht"])
        except (KeyError, TypeError, ValueError):
            ht = 0
        if 1 <= ht <= 64:
            return ht
        logging.getLogger("bigdl_tpu.ops").warning(
            "ignoring illegal lrn tuning record %s for c=%d n=%d",
            cfg, c, n)
    ht = _HW_TILE
    while ht > 1 and ht * c * n * 4 > _TEMP_BUDGET:
        ht //= 2
    return ht


def _sublane(dtype) -> int:
    return 16 if jnp.dtype(dtype).itemsize == 2 else 8


def lrn_supported(x) -> bool:
    """Kernel constraints: TPU backend, NCHW with C a full sublane tile,
    and a batch that fills the lane axis (the (H*W, C, N) view puts N on
    lanes — below ~half a lane tile the XLA fallback path wins)."""
    return (jax.default_backend() == "tpu" and x.ndim == 4
            and x.shape[1] % _sublane(x.dtype) == 0
            and x.shape[0] >= 64)


def _band_matrix(c, size, adjoint=False):
    """(C, C) 0/1 band: out[i] = sum_j band[i, j] * v[j] is the size-wide
    channel-window sum. ``adjoint`` transposes the (asymmetric, for even
    sizes) window — the backward sum over windows covering a position."""
    half = (size - 1) // 2
    lo, hi = (half, size - 1 - half)
    if adjoint:
        lo, hi = hi, lo
    i = np.arange(c)[:, None]
    j = np.arange(c)[None, :]
    return ((j - i >= -lo) & (j - i <= hi)).astype(np.float32)


def _window_sum(v, band):
    """Channel-window sum along axis 1 of a (HT, C, N) block.

    Computed as a banded (C, C) matmul per spatial row: on TPU the window
    sum becomes a tiny MXU op instead of ``size`` sublane-shifted adds —
    the shift/concat form measured 254 GB/s on the backward kernel
    (sublane rotates across vreg boundaries serialize on the VPU); the
    band-matmul form runs at HBM speed (docs/PERF.md round 3)."""
    return jnp.einsum("dc,hcn->hdn", band, v,
                      preferred_element_type=jnp.float32)


def _fwd_kernel(x_ref, band_ref, y_ref, *, size, alpha, beta, k, relu):
    x = x_ref[...].astype(jnp.float32)
    if relu:   # fused ReLU -> LRN: saves the standalone elementwise pass
        x = jnp.maximum(x, 0.0)
    s = k + (alpha / size) * _window_sum(jnp.square(x), band_ref[...])
    y_ref[...] = (x * _pow_neg_beta(s, beta)).astype(y_ref.dtype)


def _bwd_kernel(g_ref, x_ref, band_ref, adj_ref, dx_ref, *,
                size, alpha, beta, k, relu):
    # dr_i = g_i*s_i^-b - (2ab/n) * r_i * sum_win(g_j * r_j * s_j^-(b+1));
    # with fused relu r = max(x, 0) and dx = dr * 1[x > 0]
    g = g_ref[...].astype(jnp.float32)
    x = x_ref[...].astype(jnp.float32)
    r = jnp.maximum(x, 0.0) if relu else x
    s = k + (alpha / size) * _window_sum(jnp.square(r), band_ref[...])
    sb = _pow_neg_beta(s, beta)
    acc = _window_sum(g * r * sb / s, adj_ref[...])
    dx = g * sb - (2.0 * alpha * beta / size) * r * acc
    if relu:
        dx = jnp.where(x > 0.0, dx, 0.0)
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _call(kernel, args, bands, hw, c, n, dtype, interpret):
    ht = _pick_hw_tile(c, n)
    grid = (pl.cdiv(hw, ht),)
    spec = pl.BlockSpec((ht, c, n), lambda t: (t, 0, 0))
    band_spec = pl.BlockSpec((c, c), lambda t: (0, 0))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((hw, c, n), dtype),
        grid=grid,
        in_specs=[spec] * len(args) + [band_spec] * len(bands),
        out_specs=spec,
        interpret=interpret,
    )(*args, *[jnp.asarray(b) for b in bands])


def _to_view(x):
    """NCHW -> (H*W, C, N): row-major over the conv activations' native
    {0,1,3,2} physical layout, so XLA folds it to a bitcast."""
    n, c, h, w = x.shape
    return jnp.transpose(x, (2, 3, 1, 0)).reshape(h * w, c, n)


def _from_view(y, shape):
    n, c, h, w = shape
    return jnp.transpose(y.reshape(h, w, c, n), (3, 2, 0, 1))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def lrn(x, size=5, alpha=1.0, beta=0.75, k=1.0, interpret=False,
        relu=False):
    """Cross-map LRN over NCHW via the fused Pallas kernel. ``relu=True``
    applies ReLU first inside the same HBM pass (y = lrn(max(x, 0)))."""
    n, c, h, w = x.shape
    kern = functools.partial(_fwd_kernel, size=size, alpha=alpha, beta=beta,
                             k=k, relu=relu)
    y = _call(kern, (_to_view(x),), (_band_matrix(c, size),),
              h * w, c, n, x.dtype, interpret)
    return _from_view(y, x.shape)


def _lrn_fwd(x, size, alpha, beta, k, interpret, relu):
    return lrn(x, size, alpha, beta, k, interpret, relu), x


def _lrn_bwd(size, alpha, beta, k, interpret, relu, x, g):
    n, c, h, w = x.shape
    kern = functools.partial(_bwd_kernel, size=size, alpha=alpha, beta=beta,
                             k=k, relu=relu)
    dx = _call(kern, (_to_view(g), _to_view(x)),
               (_band_matrix(c, size), _band_matrix(c, size, adjoint=True)),
               h * w, c, n, x.dtype, interpret)
    return (_from_view(dx, x.shape),)


lrn.defvjp(_lrn_fwd, _lrn_bwd)

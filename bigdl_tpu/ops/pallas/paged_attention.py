"""Pallas paged-attention decode kernel: attend straight off the KV
page pool (ISSUE 9 tentpole).

Why this kernel exists: the serving stack's paged decode used to
consume the page pool through ``_paged_view`` — a gather of the ENTIRE
(B, P) block table into a dense (B, P*S, KV, D) cache copy per layer,
per decode step — and then run grouped attention over that copy. That
is an O(B·P·S·KV·D) HBM materialization (gather write + attention
re-read) to score ONE new token per row. This kernel walks each row's
block table page-by-page with an online softmax instead:

- grid ``(B*KV, T_blocks, P)``: each program loads one PHYSICAL page
  ``(S, D)`` for one (row, kv-head) pair — the page index comes from
  the scalar-prefetched block table, so the logical->physical hop
  happens in the BlockSpec index map and no dense view ever exists;
- scratch carries the flash-style running (max, sum, acc) across the
  page walk; per-row length/causal masking uses the scalar-prefetched
  ``q_start`` (query column t sits at absolute position q_start+t and
  may attend keys at positions <= its own);
- pages entirely past a block's last query position are skipped at the
  grid level (``pl.when``), so a 20-token row in a 4096-token table
  touches 2 pages, not 256;
- GQA/MQA head grouping rides the q block: the G query heads sharing a
  kv head fold into the matmul's row dimension, padded to ``gp`` rows
  (sublane alignment; padded rows are sliced off host-side).

Tile picking follows the house idiom (flash/fused_ce/lrn/maxpool): an
autotuned record in ``bigdl_tpu/tuning`` for this (t, g, s, d, device
kind) wins when legal; the static default otherwise. ``interpret=True``
runs the identical program on CPU — tier-1 pins numeric parity against
the dense ``_paged_view`` + ``_attend_grouped`` reference there
(tests/test_paged_attention.py).

The same kernel also serves DENSE per-row caches (the ragged /
speculative machinery): a (B, M, KV, D) cache is a page pool of
``M // page`` contiguous pages per row with an identity block table —
``dense_cache_attention`` builds that view (a free reshape, no copy)
so the speculative verify/decode steps ride the same switch.
"""
from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["paged_attention", "dense_cache_attention", "paged_supported",
           "dense_cache_supported", "dense_cache_page_size"]

logger = logging.getLogger("bigdl_tpu.ops")

_NEG = -1e9  # finite mask value, matches serving.py's _attend_grouped

#: static query-block menu: largest divisor of T wins (T is 1 for
#: decode, the prompt bucket for prefill, gamma+1 for speculative
#: verify) — bt*gp rows per program tile keep the score tile small.
_BT_CAP = 8
#: group rows are padded to a multiple of the f32 sublane tile so the
#: (bt*gp, S) score tile is Mosaic-aligned; padded rows cost VPU lanes,
#: not correctness (their outputs are sliced off).
_GP_ALIGN = 8


def _static_tiles(t: int, g: int) -> tuple[int, int]:
    bt = next((b for b in range(min(_BT_CAP, t), 0, -1) if t % b == 0), 1)
    gp = -(-g // _GP_ALIGN) * _GP_ALIGN
    return bt, gp


def _pick_tiles(t: int, g: int, s: int, d: int) -> tuple[int, int]:
    """(bt, gp) for this geometry: tuned record first (kernel
    ``paged_attention``, signature {t, g, s, d}), static default
    otherwise. An illegal record — bt not dividing T, gp below the real
    group count — is ignored with a warning, never an error."""
    from bigdl_tpu.tuning.records import default_records
    cfg = default_records().lookup("paged_attention",
                                   {"t": t, "g": g, "s": s, "d": d})
    if cfg:
        try:
            bt, gp = int(cfg["bt"]), int(cfg["gp"])
        except (KeyError, TypeError, ValueError):
            bt = gp = 0
        if 1 <= bt <= t and t % bt == 0 and gp >= g:
            return bt, gp
        logger.warning("ignoring illegal paged_attention tuning record "
                       "%s for t=%d g=%d s=%d d=%d", cfg, t, g, s, d)
    return _static_tiles(t, g)


def paged_supported(head_dim: int, page_size: int) -> bool:
    """Compiled-kernel constraints for the auto switch: TPU backend, a
    head dim Mosaic tiles cleanly (multiple of 64, like flash), and a
    page size on the f32 sublane grid. ``interpret=True`` has no such
    constraints — the interpreter runs any geometry (the CPU parity
    path)."""
    return (jax.default_backend() == "tpu"
            and head_dim % 64 == 0
            and page_size % 8 == 0)


def dense_cache_page_size(max_len: int, cap: int = 128,
                          floor: int = 8) -> int:
    """Page size the dense-cache view splits a (B, M, KV, D) cache
    into: the largest divisor of M in [floor, cap] — below the floor
    the per-page program overhead beats the skipping win, so an
    awkward M (e.g. prime) degrades to one M-wide page per row instead
    (still no copy, just no page skipping)."""
    return next((s for s in range(min(cap, max_len), floor - 1, -1)
                 if max_len % s == 0), max_len)


def dense_cache_supported(head_dim: int, max_len: int) -> bool:
    """Auto-switch legality for the dense-cache (ragged/speculative)
    view on the compiled path."""
    return paged_supported(head_dim, dense_cache_page_size(max_len))


def _kernel(table_ref, qstart_ref, q_ref, k_ref, v_ref, o_ref,
            m_scr, l_scr, acc_scr, *, scale, kv, n_pages, bt, gp, s):
    j = pl.program_id(2)        # logical page within the row's table
    ti = pl.program_id(1)       # query time-block
    b = pl.program_id(0) // kv  # batch row

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qs = qstart_ref[b]

    def _compute():
        d = q_ref.shape[-1]
        q = q_ref[0].reshape(bt * gp, d)            # (R, D)
        k = k_ref[0, :, 0, :]                       # (S, D)
        v = v_ref[0, :, 0, :]
        # matmuls stay in the pool dtype (bf16 full-rate on the MXU),
        # f32 accumulation — the flash kernel's round-3 lesson
        sc = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32
                                 ) * scale
        rows = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 0)
        kpos = j * s + jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
        qpos = qs + ti * bt + rows // gp
        sc = jnp.where(kpos > qpos, _NEG, sc)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(sc - m_new)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = m_new

    # a page whose first slot sits past the block's LAST query position
    # contributes exactly zero (every key masked) — skip its DMA+FLOPs
    pl.when(j * s <= qs + ti * bt + bt - 1)(_compute)

    @pl.when(j == n_pages - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / l_scr[:]).reshape(bt, gp,
                                                   o_ref.shape[-1])


def paged_attention(q, kp, vp, table, q_start, *, scale=None,
                    bt: int | None = None, gp: int | None = None,
                    interpret: bool = False):
    """Grouped causal attention of ``q`` (B, T, H, D) directly against
    the page pool — no dense per-row cache view is materialized.

    ``kp``/``vp``: (num_pages, S, KV, D) physical pools; ``table``:
    (B, P) logical->physical page ids (every entry must be a legal pool
    index — the serving layer's tables are); ``q_start``: (B,) absolute
    position of each row's FIRST query column — column t sits at
    q_start+t and attends key positions <= q_start+t (exactly
    ``_attend_grouped``'s ``upto`` mask for the serving layer's
    column layouts). Returns (B, T, H, D) f32.

    Tiles come from the autotuned record store unless ``bt``/``gp``
    override them. ``interpret=True`` runs the interpreter (the CPU
    parity path tier-1 pins).
    """
    b, t, h, d = q.shape
    n_pool, s, kv, _ = kp.shape
    if h % kv:
        raise ValueError(f"{h} query heads not divisible by {kv} kv "
                         "heads")
    g = h // kv
    p = table.shape[1]
    scale = d ** -0.5 if scale is None else scale
    pbt, pgp = _pick_tiles(t, g, s, d)
    bt = pbt if bt is None else bt
    gp = pgp if gp is None else gp
    if t % bt or gp < g:
        raise ValueError(f"illegal tiles bt={bt} gp={gp} for t={t} "
                         f"g={g}")
    from jax.experimental.pallas import tpu as pltpu
    qg = q.astype(kp.dtype).reshape(b, t, kv, g, d)
    if gp > g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, 0), (0, gp - g), (0, 0)))
    qf = qg.transpose(0, 2, 1, 3, 4).reshape(b * kv, t, gp, d)

    def qmap(bk, ti, j, table_ref, qstart_ref):
        return (bk, ti, 0, 0)

    def kvmap(bk, ti, j, table_ref, qstart_ref):
        # the logical->physical hop: one scalar-prefetched table probe
        # per block, never a gathered view
        return (table_ref[bk // kv, j], 0, bk % kv, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * kv, t // bt, p),
        in_specs=[pl.BlockSpec((1, bt, gp, d), qmap),
                  pl.BlockSpec((1, s, 1, d), kvmap),
                  pl.BlockSpec((1, s, 1, d), kvmap)],
        out_specs=pl.BlockSpec((1, bt, gp, d), qmap),
        scratch_shapes=[pltpu.VMEM((bt * gp, 1), jnp.float32),
                        pltpu.VMEM((bt * gp, 1), jnp.float32),
                        pltpu.VMEM((bt * gp, d), jnp.float32)])
    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale, kv=kv, n_pages=p, bt=bt,
                          gp=gp, s=s),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * kv, t, gp, d), jnp.float32),
        interpret=interpret,
    )(table.astype(jnp.int32), q_start.astype(jnp.int32), qf, kp, vp)
    return (out.reshape(b, kv, t, gp, d)[:, :, :, :g]
            .transpose(0, 2, 1, 3, 4).reshape(b, t, h, d))


def dense_cache_attention(q, ck, cv, q_start, *, scale=None,
                          interpret: bool = False):
    """The kernel over a DENSE per-row cache (B, M, KV, D) — the
    ragged/speculative layout. The cache IS a page pool of ``M // S``
    contiguous pages per row (a reshape, not a copy) with the identity
    block table, so the same online-softmax walk applies and short rows
    still skip their empty tail pages."""
    b, m, kv, d = ck.shape
    s = dense_cache_page_size(m)
    n = m // s
    pool_k = ck.reshape(b * n, s, kv, d)
    pool_v = cv.reshape(b * n, s, kv, d)
    table = (jnp.arange(b, dtype=jnp.int32)[:, None] * n
             + jnp.arange(n, dtype=jnp.int32)[None, :])
    return paged_attention(q, pool_k, pool_v, table, q_start,
                           scale=scale, interpret=interpret)

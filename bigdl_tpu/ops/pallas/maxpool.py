"""Pallas TPU backward kernel for stride-1 SAME 3x3 max pooling.

Why (VERDICT r3 #2): the round-3 HLO audit of the Inception-v1 train step
put the 13 pool ops at ~10 ms of a 50 ms step — 3.7 GB of
select-and-scatter backward + 2.2 GB of forward, already at near-minimal
IO, so the remaining cost is S&S *execution* inefficiency, not bytes.
The three round-2 hand-written VJPs were XLA-graph rewrites and all
measured slower end-to-end (docs/PERF.md); this kernel is the never-tried
fourth option: one fused Pallas pass for the backward.

MEASURED OUTCOME (round 4, v5e batch 256): 4,437-4,439 img/s on the
Inception bench vs 5,056-5,252 for plain select-and-scatter autodiff —
REJECTED for dispatch (nn/pooling.py keeps S&S; this file stays as the
recorded experiment with interpret-mode parity tests). Root cause: the
first-max mask formulation costs ~45 VPU ops per element (9 compares +
running-OR + select + 9 shifted adds); across the nine in-block pools
that is ~238M elements/step ≈ 10 ms of pure VPU work — the backward is
COMPUTE-bound on the vector unit, while XLA's S&S executes on a
hardware path that is not. The round-3 audit's "S&S inefficiency"
hypothesis is thereby falsified: S&S was already at the achievable
floor. Tuning knobs tried: H-tile 4/2/whole-plane, c-tile 8/16 (bf16
compares are unsupported by Mosaic, forcing f32 temps and small tiles).

Scope: the nine IN-BLOCK pools (3x3, stride 1, SAME padding) — the
majority of pool traffic; the stride-2 stem pools keep XLA S&S.
Forward stays ``lax.reduce_window`` (minimal IO, efficient); only the
backward is replaced, via ``jax.custom_vjp``:

    dx[p] = sum_o  dy[j] * take_o[j],   p = j + offset_o
    take_o[j] = (x[j + offset_o] == y[j]) and no earlier o' matched

— the first-max tie rule in row-major window order, exactly Torch's and
XLA S&S's semantics (reference nn/SpatialMaxPooling.scala backward loop).
Using the forward's y as a residual means no in-kernel max recompute.

Layout (the LRN playbook, ops/pallas/lrn.py): the kernel consumes a
(H, W, C, N) VIEW of NCHW — row-major over XLA's native {0,1,3,2} conv
activation layout, so the transpose folds to a bitcast. C rides sublanes,
N rides lanes; W needs no alignment (major dim). H is tiled with 2-row
(x) / 1-row (y, dy) halo BLOCKS — overlapping windows can't be expressed
as disjoint BlockSpecs, so the halos are extra one-off block inputs whose
index maps clamp at the array edge and whose out-of-range rows are masked
in-kernel (x -> -inf, dy -> 0, reproducing SAME padding).
"""
from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["maxpool3x3s1", "maxpool3x3s1_supported"]


def _sublane(dtype) -> int:
    return 16 if jnp.dtype(dtype).itemsize == 2 else 8


# lane-axis tile: N beyond this is gridded
_N_TILE = 256
# H tile for large spatial planes (28x28 at Inception widths); small
# planes (H <= 16: the 14x14 and 7x7 pools) run whole-plane
_H_TILE = 4


def maxpool3x3s1_supported(x) -> bool:
    """Kernel constraints: TPU, NCHW, C a full sublane tile, N a full
    lane tile (or a multiple), and H either small or H-tile divisible."""
    if not (jax.default_backend() == "tpu" and x.ndim == 4):
        return False
    n, c, h, w = x.shape
    return c % 8 == 0 and n % 128 == 0


def _bwd_kernel(x_ref, xt_ref, xb_ref, y_ref, yt_ref, yb_ref,
                g_ref, gt_ref, gb_ref, dx_ref, *, h, h_t, n_h):
    """One (H-tile, C-tile, N-tile) program.

    Row coordinate systems (local to this program; ht = rows of out):
      out rows   p  : 0 .. ht-1            (global h_i*ht + p)
      windows    j  : -1 .. ht             (y/dy rows, 1-row halos)
      x rows        : -2 .. ht+1           (2-row halo blocks)
    """
    h_i = pl.program_id(0)
    # comparisons run in f32 — Mosaic's TPU target rejects bf16 vector
    # compares ("Target does not support this comparison"); the bf16->f32
    # cast is exact so first-max semantics are unchanged
    neg = jnp.finfo(jnp.float32).min

    # assemble x rows [-2, ht+1], mask out-of-image rows to -inf (SAME pad)
    x_all = jnp.concatenate([xt_ref[...], x_ref[...],
                             xb_ref[...]], axis=0).astype(jnp.float32)
    rows_x = jax.lax.broadcasted_iota(
        jnp.int32, x_all.shape, 0) + h_i * h_t - 2
    x_all = jnp.where((rows_x >= 0) & (rows_x < h), x_all, neg)

    # y / dy rows [-1, ht]; OOB dy rows -> 0 (their windows don't exist)
    y_all = jnp.concatenate([yt_ref[...], y_ref[...],
                             yb_ref[...]], axis=0).astype(jnp.float32)
    g_all = jnp.concatenate([gt_ref[...], g_ref[...], gb_ref[...]], axis=0)
    rows_j = jax.lax.broadcasted_iota(
        jnp.int32, g_all.shape, 0) + h_i * h_t - 1
    g_all = jnp.where((rows_j >= 0) & (rows_j < h), g_all, 0)

    w_ = x_ref.shape[1]
    # W pads: x by 2 (-inf), y/dy by 1 (-inf / 0) — window cols j_c in
    # [-1, W] read x cols [-2, W+1]; -inf pad reproduces SAME padding and
    # can only "match" a -inf y, whose dy is 0
    pad4 = [(0, 0)] * 2
    x_p = jnp.pad(x_all, [(0, 0), (2, 2)] + pad4, constant_values=neg)
    y_p = jnp.pad(y_all, [(0, 0), (1, 1)] + pad4, constant_values=neg)
    g_p = jnp.pad(g_all, [(0, 0), (1, 1)] + pad4)

    jr, jc = h_t + 2, w_ + 2                 # window-grid extent
    cum = jnp.zeros(y_p.shape, jnp.bool_)
    # dx accumulator over p rows [-2, ht+1], cols [-2, W+1] (then crop)
    acc = jnp.zeros((h_t + 4, w_ + 4) + x_all.shape[2:], g_ref.dtype)
    for dr in (-1, 0, 1):                    # row-major window order ==
        for dc in (-1, 0, 1):                # torch first-max tie rule
            v = jax.lax.slice(
                x_p, (1 + dr, 1 + dc, 0, 0),
                (1 + dr + jr, 1 + dc + jc) + x_p.shape[2:])
            take = (v == y_p) & ~cum
            cum = cum | take
            contrib = jnp.where(take, g_p, 0)
            # place contrib at offset (1+dr, 1+dc) in the acc extent via a
            # static pad (dynamic_update_slice has no Pallas TPU lowering)
            acc = acc + jnp.pad(
                contrib, [(1 + dr, 1 - dr), (1 + dc, 1 - dc),
                          (0, 0), (0, 0)])
    dx_ref[...] = jax.lax.slice(
        acc, (2, 2, 0, 0),
        (2 + h_t, 2 + w_) + acc.shape[2:]).astype(dx_ref.dtype)


def _pick_tiles(hw_h: int, n: int) -> tuple[int, int]:
    """(H-tile, N-tile): tuned record for this (H, N, device kind)
    first, the swept static defaults otherwise."""
    from bigdl_tpu.tuning.records import default_records
    cfg = default_records().lookup("maxpool3x3s1", {"h": hw_h, "n": n})
    if cfg:
        try:
            h_t, n_t = int(cfg["h_t"]), int(cfg["n_t"])
        except (KeyError, TypeError, ValueError):
            h_t = n_t = 0
        if (1 <= h_t <= hw_h and hw_h % h_t == 0
                and 1 <= n_t <= n and n % n_t == 0):
            return h_t, n_t
        logging.getLogger("bigdl_tpu.ops").warning(
            "ignoring illegal maxpool tuning record %s for h=%d n=%d",
            cfg, hw_h, n)
    # in-kernel temps are f32 (Mosaic can't compare bf16 vectors), so H
    # tiles stay small; odd H (the 7x7 pools) runs whole-plane
    if hw_h % _H_TILE == 0:
        h_t = _H_TILE
    elif hw_h % 2 == 0:
        h_t = 2
    else:
        h_t = hw_h
    return h_t, min(n, _N_TILE)


def _bwd_call(x, y, g, interpret):
    hw_h, w_, c, n = x.shape        # (H, W, C, N) view
    h_t, n_t = _pick_tiles(hw_h, n)
    n_h = pl.cdiv(hw_h, h_t)
    c_t = 8
    grid = (n_h, c // c_t, n // n_t)

    def main_spec(rows):
        return pl.BlockSpec((rows, w_, c_t, n_t),
                            lambda hi, ci, ni: (hi, 0, ci, ni))

    def halo_spec(rows, offset_rows, max_block):
        # block index in units of `rows`; clamped at the edges (the
        # kernel masks the out-of-range rows)
        def index(hi, ci, ni):
            blk = (hi * h_t + offset_rows) // rows
            return (jnp.clip(blk, 0, max_block), 0, ci, ni)
        return pl.BlockSpec((rows, w_, c_t, n_t), index)

    max2 = (hw_h + 1) // 2 - 1      # last valid 2-row block index
    kern = functools.partial(_bwd_kernel, h=hw_h, h_t=h_t, n_h=n_h)
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=grid,
        in_specs=[
            main_spec(h_t),                      # x main
            halo_spec(2, -2, max2),              # x rows -2..-1
            halo_spec(2, h_t, max2),             # x rows ht..ht+1
            main_spec(h_t),                      # y main
            halo_spec(1, -1, hw_h - 1),          # y row -1
            halo_spec(1, h_t, hw_h - 1),         # y row ht
            main_spec(h_t),                      # dy main
            halo_spec(1, -1, hw_h - 1),          # dy row -1
            halo_spec(1, h_t, hw_h - 1),         # dy row ht
        ],
        out_specs=main_spec(h_t),
        interpret=interpret,
    )(x, x, x, y, y, y, g, g, g)


def _to_view(t):
    """NCHW -> (H, W, C, N): row-major over the conv activations' native
    {0,1,3,2} physical layout, so XLA folds it to a bitcast."""
    return jnp.transpose(t, (2, 3, 1, 0))


def _from_view(t):
    return jnp.transpose(t, (3, 2, 0, 1))


def _fwd_xla(x):
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, window_dimensions=(1, 1, 3, 3),
        window_strides=(1, 1, 1, 1),
        padding=((0, 0), (0, 0), (1, 1), (1, 1)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def maxpool3x3s1(x, interpret=False):
    """3x3 / stride-1 / SAME max pool over NCHW. Forward is XLA
    ``reduce_window``; backward is the fused Pallas kernel (bit-exact
    first-max semantics, no select-and-scatter)."""
    return _fwd_xla(x)


def _mp_fwd(x, interpret):
    y = _fwd_xla(x)
    return y, (x, y)


def _mp_bwd(interpret, res, g):
    x, y = res
    dx = _bwd_call(_to_view(x), _to_view(y), _to_view(g), interpret)
    return (_from_view(dx),)


maxpool3x3s1.defvjp(_mp_fwd, _mp_bwd)

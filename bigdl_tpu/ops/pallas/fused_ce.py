"""Fused LM-head + cross-entropy Pallas TPU kernel (chunked over vocab).

Why this kernel exists (round-3 LM trace, docs/PERF.md): at vocab 32k /
S=2048 / batch 8, the unfused loss path materializes the (B, S, V) logits
THREE times — the bf16 head-GEMM output (1 GB), an f32 convert the
softmax statistics read (2.15 GB — XLA materializes it because lse, max
and the target gather all consume it), and the bf16 dlogits cotangent
(1 GB) — ~10 ms of the 44.5 ms step. This kernel streams vocab tiles
through VMEM with an online logsumexp, exactly like flash attention
streams K/V tiles, so full logits never exist:

- forward:  read h (N, D), W (V, D), b — emit per-token nll and lse.
- backward: recompute the logits tile-by-tile from (h, W, lse) and
  accumulate dh (tokens outer, vocab inner) and dW/db (vocab outer,
  tokens inner) in two passes — one extra head-GEMM of FLOPs in exchange
  for ~4 GB less HBM traffic per step.

Matmuls run in the storage dtype (bf16 on the MXU, f32 accumulation);
softmax statistics are f32 in VMEM. Targets are 1-based, matching the
reference's ClassNLLCriterion convention (nn/ClassNLLCriterion.scala).

This is a training-path op for big-vocab LMs; the module-level
``CrossEntropyCriterion`` (nn/criterion.py) remains the general API.
"""
from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["linear_cross_entropy", "linear_ce_supported"]

logger = logging.getLogger("bigdl_tpu.ops")

# token/vocab tiles: the (BT, BV) f32 logits tile plus double-buffered
# W tiles must fit the 16 MB VMEM budget — 512x1024 keeps the dh kernel
# at ~8 MB with bf16 W at D=512 (1024x2048 OOMed on v5e). The menu is
# the fallback — an autotuned record (bigdl_tpu/tuning) for this
# (tokens, vocab, device kind) wins when one exists and is legal.
_T_BLOCKS = (512, 256, 128)
_V_BLOCKS = (1024, 512, 256, 128)


def _pick(n, menu):
    return next((b for b in menu if n % b == 0), None)


def _pick_tiles(n: int, v: int) -> tuple[int, int]:
    """(BT, BV) for (tokens, vocab): tuned record first, static menu
    otherwise. Used identically by forward and both backward kernels so
    a tuning record retiles the whole op."""
    from bigdl_tpu.tuning.records import default_records
    cfg = default_records().lookup("fused_ce", {"n": n, "v": v})
    if cfg:
        try:
            bt, bv = int(cfg["bt"]), int(cfg["bv"])
        except (KeyError, TypeError, ValueError):
            bt = bv = 0
        if bt >= 8 and bv >= 128 and n % bt == 0 and v % bv == 0:
            return bt, bv
        logger.warning("ignoring illegal fused_ce tuning record %s "
                       "for n=%d v=%d", cfg, n, v)
    return _pick(n, _T_BLOCKS), _pick(v, _V_BLOCKS)


def _tiles_ok(h, w) -> bool:
    return (h.shape[0] % _T_BLOCKS[-1] == 0
            and w.shape[0] % _V_BLOCKS[-1] == 0
            and h.shape[1] % 128 == 0)


def linear_ce_supported(h, w) -> bool:
    """TPU backend with tile-divisible token count / vocab and a
    lane-tileable feature dim."""
    return jax.default_backend() == "tpu" and _tiles_ok(h, w)


def _logits_tile(h_ref, w_ref, b_ref):
    s = jax.lax.dot_general(h_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return s + b_ref[...]


def _onehot_tile(t_ref, vi, bt, bv):
    """(BT, BV) one-hot of the (1-based) targets within vocab tile vi."""
    col = jax.lax.broadcasted_iota(jnp.int32, (bt, bv), 1) + vi * bv
    return (col == t_ref[...] - 1).astype(jnp.float32)


def _fwd_kernel(h_ref, w_ref, b_ref, t_ref, nll_ref, lse_ref,
                m_scr, l_scr, tl_scr, *, nv, bt, bv):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        tl_scr[:] = jnp.zeros_like(tl_scr)

    s = _logits_tile(h_ref, w_ref, b_ref)
    m_prev = m_scr[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    l_scr[:] = l_scr[:] * jnp.exp(m_prev - m_new) + \
        jnp.sum(jnp.exp(s - m_new), axis=1, keepdims=True)
    m_scr[:] = m_new
    tl_scr[:] = tl_scr[:] + jnp.sum(
        s * _onehot_tile(t_ref, vi, bt, bv), axis=1, keepdims=True)

    @pl.when(vi == nv - 1)
    def _finalize():
        lse = m_scr[:] + jnp.log(l_scr[:])
        lse_ref[...] = lse
        nll_ref[...] = lse - tl_scr[:]


def _dh_kernel(h_ref, w_ref, b_ref, t_ref, lse_ref, g_ref, dh_ref,
               dh_scr, *, nv, bt, bv):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)

    s = _logits_tile(h_ref, w_ref, b_ref)
    dlogits = (jnp.exp(s - lse_ref[...])
               - _onehot_tile(t_ref, vi, bt, bv)) * g_ref[...]
    dh_scr[:] = dh_scr[:] + jax.lax.dot_general(
        dlogits.astype(w_ref.dtype), w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(vi == nv - 1)
    def _finalize():
        dh_ref[...] = dh_scr[:].astype(dh_ref.dtype)


def _dw_kernel(h_ref, w_ref, b_ref, t_ref, lse_ref, g_ref,
               dw_ref, db_ref, dw_scr, db_scr, *, nt, bt, bv):
    ti = pl.program_id(1)
    vi = pl.program_id(0)

    @pl.when(ti == 0)
    def _init():
        dw_scr[:] = jnp.zeros_like(dw_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    s = _logits_tile(h_ref, w_ref, b_ref)
    dlogits = (jnp.exp(s - lse_ref[...])
               - _onehot_tile(t_ref, vi, bt, bv)) * g_ref[...]
    dw_scr[:] = dw_scr[:] + jax.lax.dot_general(
        dlogits.astype(h_ref.dtype), h_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    db_scr[:] = db_scr[:] + jnp.sum(dlogits, axis=0, keepdims=True)

    @pl.when(ti == nt - 1)
    def _finalize():
        dw_ref[...] = dw_scr[:].astype(dw_ref.dtype)
        db_ref[...] = db_scr[:].astype(db_ref.dtype)


def _specs(bt, bv, d):
    h_spec = pl.BlockSpec((bt, d), lambda t, v: (t, 0))
    w_spec = pl.BlockSpec((bv, d), lambda t, v: (v, 0))
    b_spec = pl.BlockSpec((1, bv), lambda t, v: (0, v))
    t_spec = pl.BlockSpec((bt, 1), lambda t, v: (t, 0))
    return h_spec, w_spec, b_spec, t_spec


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _linear_ce(h, w, b, targets, interpret):
    nll, _ = _forward(h, w, b, targets, interpret)
    return nll


def _forward(h, w, b, targets, interpret):
    from jax.experimental.pallas import tpu as pltpu
    n, d = h.shape
    v = w.shape[0]
    bt, bv = _pick_tiles(n, v)
    nt, nv = n // bt, v // bv
    h_spec, w_spec, b_spec, t_spec = _specs(bt, bv, d)
    nll, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, nv=nv, bt=bt, bv=bv),
        grid=(nt, nv),
        in_specs=[h_spec, w_spec, b_spec, t_spec],
        out_specs=[t_spec, t_spec],
        out_shape=[jax.ShapeDtypeStruct((n, 1), jnp.float32),
                   jax.ShapeDtypeStruct((n, 1), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bt, 1), jnp.float32)] * 3,
        interpret=interpret,
    )(h, w, b.reshape(1, v), targets.reshape(n, 1).astype(jnp.int32))
    return nll[:, 0], lse


def _linear_ce_fwd(h, w, b, targets, interpret):
    nll, lse = _forward(h, w, b, targets, interpret)
    return nll, (h, w, b, targets, lse)


def _linear_ce_bwd(interpret, res, g):
    from jax.experimental.pallas import tpu as pltpu
    h, w, b, targets, lse = res
    n, d = h.shape
    v = w.shape[0]
    bt, bv = _pick_tiles(n, v)
    nt, nv = n // bt, v // bv
    h_spec, w_spec, b_spec, t_spec = _specs(bt, bv, d)
    g2 = g.reshape(n, 1).astype(jnp.float32)
    t2 = targets.reshape(n, 1).astype(jnp.int32)
    b2 = b.reshape(1, v)

    dh = pl.pallas_call(
        functools.partial(_dh_kernel, nv=nv, bt=bt, bv=bv),
        grid=(nt, nv),
        in_specs=[h_spec, w_spec, b_spec, t_spec, t_spec, t_spec],
        out_specs=h_spec,
        out_shape=jax.ShapeDtypeStruct(h.shape, h.dtype),
        scratch_shapes=[pltpu.VMEM((bt, d), jnp.float32)],
        interpret=interpret,
    )(h, w, b2, t2, lse, g2)

    # vocab tiles outer, token tiles inner — dW/db accumulate over tokens
    h_spec_w = pl.BlockSpec((bt, d), lambda v_, t: (t, 0))
    w_spec_w = pl.BlockSpec((bv, d), lambda v_, t: (v_, 0))
    b_spec_w = pl.BlockSpec((1, bv), lambda v_, t: (0, v_))
    t_spec_w = pl.BlockSpec((bt, 1), lambda v_, t: (t, 0))
    db_spec = pl.BlockSpec((1, bv), lambda v_, t: (0, v_))
    dw, db = pl.pallas_call(
        functools.partial(_dw_kernel, nt=nt, bt=bt, bv=bv),
        grid=(nv, nt),
        in_specs=[h_spec_w, w_spec_w, b_spec_w, t_spec_w, t_spec_w,
                  t_spec_w],
        out_specs=[w_spec_w, db_spec],
        out_shape=[jax.ShapeDtypeStruct(w.shape, w.dtype),
                   jax.ShapeDtypeStruct((1, v), b.dtype)],
        scratch_shapes=[pltpu.VMEM((bv, d), jnp.float32),
                        pltpu.VMEM((1, bv), jnp.float32)],
        interpret=interpret,
    )(h, w, b2, t2, lse, g2)
    return dh, dw, db.reshape(v), None


_linear_ce.defvjp(_linear_ce_fwd, _linear_ce_bwd)


def linear_cross_entropy(h, w, b, targets, *, reduction: str = "mean",
                         use_kernel: str | bool = "auto",
                         interpret: bool = False):
    """Cross-entropy over ``logits = h @ w.T + b`` WITHOUT materializing
    the logits (kernel path), for (N, D) activations, (V, D) torch-layout
    weight, (V,) bias (or None) and 1-based integer ``targets`` (N,).

    ``use_kernel``: "auto" picks the Pallas path on TPU when shapes tile
    (``linear_ce_supported``); True forces it (raises otherwise); False
    uses the XLA fallback (identical math, materialized logits).
    Returns the scalar mean (or summed) negative log-likelihood.

    Contract: targets must lie in ``[1, V]`` (1-based, reference
    ClassNLLCriterion convention). An out-of-contract target — e.g. a
    0 padding label — contributes ``nll = lse`` (its one-hot matches no
    class) on BOTH paths; mask padding out before calling if that is
    not the intent.
    """
    n = h.shape[0]
    bias = b if b is not None else jnp.zeros((w.shape[0],), h.dtype)
    # interpret substitutes for the TPU backend, never for the tiling
    supported = _tiles_ok(h, w) and (interpret
                                     or jax.default_backend() == "tpu")
    if use_kernel is True and not supported:
        raise ValueError(
            f"use_kernel=True but the fused CE kernel does not support "
            f"this call: backend={jax.default_backend()}, h{h.shape} "
            f"w{w.shape} (need TPU, tokens % {_T_BLOCKS[-1]} == 0, vocab "
            f"% {_V_BLOCKS[-1]} == 0, features % 128 == 0)")
    if use_kernel is not False and supported:
        nll = _linear_ce(h, w, bias, targets, interpret)
    else:
        logits = (h @ w.T.astype(h.dtype)).astype(jnp.float32) + bias
        lse = jax.nn.logsumexp(logits, axis=-1)
        t0 = targets.astype(jnp.int32) - 1
        tl = jnp.take_along_axis(
            logits, jnp.clip(t0, 0, w.shape[0] - 1)[:, None], axis=-1)[:, 0]
        # out-of-contract targets match no class — same as the kernel's
        # one-hot semantics (instead of take_along_axis index wrap-around)
        in_contract = (t0 >= 0) & (t0 < w.shape[0])
        nll = lse - jnp.where(in_contract, tl, 0.0)
    total = jnp.sum(nll)
    return total / n if reduction == "mean" else total

"""Pallas TPU kernels, each justified by a measured profile (docs/PERF.md):
``lrn`` (Inception's top HBM consumer) and ``flash_attention``
(long-context: O(S*D) memory vs the XLA path's (B,H,S,S) score matrix).
Import the submodules — their names are not re-exported here so that
``from bigdl_tpu.ops.pallas import lrn`` keeps meaning the module."""

"""Pallas TPU kernels, each justified by a measured profile (docs/PERF.md):
``lrn`` (Inception's top HBM consumer), ``flash_attention``
(long-context: O(S*D) memory vs the XLA path's (B,H,S,S) score matrix),
``fused_ce`` (the LM head), and ``paged_attention`` (serving decode
straight off the KV page pool — no dense cache-view gather).
Import the submodules — their names are not re-exported here so that
``from bigdl_tpu.ops.pallas import lrn`` keeps meaning the module."""

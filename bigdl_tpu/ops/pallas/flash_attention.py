"""Fused Pallas TPU flash attention (forward + FlashAttention-2 backward).

Why this kernel exists: the stack's naive attention core
(parallel/sequence.py `dot_product_attention`) materializes the full
(B, H, S, S) score matrix in f32 — at S=4096, H=8, B=1 that is 512 MB of
HBM traffic per direction per layer, and O(S^2) memory caps the sequence
length a chip can hold. This kernel streams K/V blocks through VMEM with
an online softmax, so HBM traffic is O(S·D) and live memory is one
(BLOCK_Q, BLOCK_K) tile per program:

- forward:  read q/k/v, write o and the per-row logsumexp — the softmax
  normalizer is the only residual beyond the layer's own inputs/outputs.
- backward: two kernels (dq; dk+dv fused) recompute probabilities from
  q/k/lse instead of loading an S×S matrix; plus an elementwise
  delta = rowsum(dO ∘ O) precomputed on the XLA path.

The construction follows the public FlashAttention/FlashAttention-2
algorithm (see PAPERS.md); causal masking skips fully-masked tiles at
the grid level. All arithmetic is f32 in VMEM; q/k/v/o touch HBM in
their own (typically bf16) dtype.

Reference scope note: the reference predates transformers (SURVEY §5.7)
— attention itself is already beyond parity; this kernel is the TPU-hot
path for the framework's long-context story (ring/Ulysses sequence
parallelism compose with it: each shard's local attention is this
kernel whenever shapes allow).
"""
from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["flash_attention", "flash_attention_with_lse",
           "flash_supported"]

logger = logging.getLogger("bigdl_tpu.ops")

# block-size menu: largest tile dividing the sequence wins — bigger tiles
# amortize grid overhead and keep the MXU busy (512x1024 measured 2.7x the
# 128x128 fwd at S=4096 on v5e); VMEM peak stays ~4 MB (s+p f32 tiles).
# The menu is the FALLBACK: a measured winner in the tuning record store
# (bigdl_tpu/tuning/) for this (sq, skv, device kind) takes precedence,
# and sequences no menu entry divides fall back to generated divisors
# (_divisor_fallback) before giving up.
_Q_BLOCKS = (512, 256, 128)
_K_BLOCKS = (1024, 512, 256, 128)


def _tuned_blocks(sq: int, skv: int) -> tuple[int, int] | None:
    """Autotuned (BQ, BK) for this geometry on this device kind, if a
    record exists and is still legal for the shapes (a stale record —
    e.g. tuned for a different sequence — is ignored with a warning,
    never an error)."""
    from bigdl_tpu.tuning.records import default_records
    cfg = default_records().lookup("flash_attention",
                                   {"sq": sq, "skv": skv})
    if not cfg:
        return None
    try:
        bq, bk = int(cfg["bq"]), int(cfg["bk"])
    except (KeyError, TypeError, ValueError):
        bq = bk = 0
    if bq >= 8 and bk >= 8 and sq % bq == 0 and skv % bk == 0:
        return bq, bk
    logger.warning("ignoring illegal flash_attention tuning record "
                   "%s for sq=%d skv=%d", cfg, sq, skv)
    return None


def _divisor_fallback(s: int, cap: int) -> int | None:
    """Largest tile legally dividing ``s`` when no menu entry does:
    multiples of 16 (the bf16 sublane tile — legal for f32 too) from
    ``cap`` down to 128. E.g. s=320 -> 160, s=384 -> 384."""
    top = min(cap, s)
    for b in range(top - top % 16, 127, -16):
        if s % b == 0:
            return b
    return None


def _blocks_or_none(sq: int, skv: int) -> tuple[int, int] | None:
    tuned = _tuned_blocks(sq, skv)
    if tuned is not None:
        return tuned
    bq = next((b for b in _Q_BLOCKS if sq % b == 0), None) \
        or _divisor_fallback(sq, _Q_BLOCKS[0])
    bk = next((b for b in _K_BLOCKS if skv % b == 0), None) \
        or _divisor_fallback(skv, _K_BLOCKS[0])
    if bq is None or bk is None:
        return None
    return bq, bk


def _pick_blocks(sq: int, skv: int) -> tuple[int, int]:
    picked = _blocks_or_none(sq, skv)
    if picked is None:
        raise ValueError(
            f"flash_attention needs sequence lengths with a tile "
            f"divisor >= 128 (multiple of 16); got q_seq={sq}, "
            f"kv_seq={skv} "
            f"(use dot_product_attention's XLA path for ragged shapes)")
    return picked

_NEG = -1e9  # finite mask value, matches parallel/sequence.py


def flash_supported(q, k) -> bool:
    """Kernel constraints: TPU backend, sequence lengths ``_pick_blocks``
    can tile (menu, tuned record, or generated divisor — this predicate
    and the picker share ``_blocks_or_none``, so supported == will not
    raise), and a head dim Mosaic tiles cleanly. D=64 — the most common
    transformer geometry — engages the kernel (round 3: Mosaic pads the
    64-lane minor dim internally; measured faster than the XLA fallback,
    which the old ``d % 128`` guard silently forced)."""
    return (jax.default_backend() == "tpu"
            and _blocks_or_none(q.shape[1], k.shape[1]) is not None
            and q.shape[-1] % 64 == 0)


def _causal_mask(s, qi, ki, bq, bk):
    """Mask s (BQ, BK) for tile (qi, ki): kpos > qpos -> _NEG."""
    qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(kpos > qpos, _NEG, s)


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, nk, bq, bk):
    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: tiles entirely above the diagonal contribute exactly zero
    # (exp(_NEG - m) underflows); skip their FLOPs at the grid level
    def _compute():
        # matmuls stay in the storage dtype (bf16 on the MXU at full rate,
        # f32 accumulation via preferred_element_type) — converting inputs
        # to f32 first runs the MXU at its 1/4-1/8 f32 rate and was the
        # round-2 kernel's S=2048 parity problem (docs/PERF.md round 3)
        s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = m_new

    if causal:
        pl.when(ki * bk <= qi * bq + bq - 1)(_compute)
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:]
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)


def _fwd(q, k, v, scale, causal, interpret):
    from jax.experimental.pallas import tpu as pltpu
    bh, sq, d = q.shape
    skv = k.shape[1]
    bq, bk = _pick_blocks(sq, skv)
    nq, nk = sq // bq, skv // bk
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal, nk=nk,
                             bq=bq, bk=bk)
    q_spec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))
    kv_spec = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0))
    o, lse = pl.pallas_call(
        kern,
        grid=(bh, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[q_spec,
                   pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
                   jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------------
# backward (FlashAttention-2): dq in one kernel, dk/dv fused in another
# --------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, scale, causal, nk, bq, bk):
    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk)
        p = jnp.exp(s - lse_ref[0])
        dp = jax.lax.dot_general(do_ref[0], v_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(ki * bk <= qi * bq + bq - 1)(_compute)
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal, nq, bq, bk):
    qi = pl.program_id(2)
    ki = pl.program_id(1)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk)
        p = jnp.exp(s - lse_ref[0])                     # (BQ, BK)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do_ref[0], v_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        # dk accumulates ds^T q * scale (ds here carries no scale; fold it
        # at the end would change dq too — apply to the addend directly)
        ds = p * (dp - delta_ref[0]) * scale            # (BQ, BK)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(ki * bk <= qi * bq + bq - 1)(_compute)
    else:
        _compute()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(scale, causal, interpret, res, g):
    """VJP for (o, lse) outputs.

    The lse cotangent folds into the existing kernels: with lse an
    output, ds_ij gains + g_lse_i * p_ij (d lse_i / d s_ij = p_ij), so
    ds = p * (dp - (delta - g_lse)) — pass delta' = delta - g_lse and
    the dq/dkdv kernels are unchanged. dv has no direct lse term.
    """
    from jax.experimental.pallas import tpu as pltpu
    q, k, v, o, lse = res
    g, g_lse = g
    bh, sq, d = q.shape
    skv = k.shape[1]
    bq, bk = _pick_blocks(sq, skv)
    nq, nk = sq // bq, skv // bk
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
    delta = delta - g_lse.astype(jnp.float32)

    q_spec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))
    kv_spec_q = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, nk=nk,
                          bq=bq, bk=bk),
        grid=(bh, nq, nk),
        in_specs=[q_spec, kv_spec_q, kv_spec_q, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    # dk/dv: grid walks q blocks innermost for each k block
    q_spec_k = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, j, 0))
    kv_spec = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, i, 0))
    row_spec_k = pl.BlockSpec((1, bq, 1),
                              lambda b, i, j: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkdv_kernel, scale=scale, causal=causal, nq=nq,
                          bq=bq, bk=bk),
        grid=(bh, nk, nq),
        in_specs=[q_spec_k, kv_spec, kv_spec, q_spec_k, row_spec_k,
                  row_spec_k],
        out_specs=[kv_spec, kv_spec],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public entry: (B, S, H, D) api matching parallel/sequence.py
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_bhsd(q, k, v, scale, causal, interpret):
    return _fwd(q, k, v, scale, causal, interpret)


def _flash_fwd(q, k, v, scale, causal, interpret):
    o, lse = _fwd(q, k, v, scale, causal, interpret)
    return (o, lse), (q, k, v, o, lse)


_flash_bhsd.defvjp(_flash_fwd, _bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: float | None = None, interpret: bool = False):
    """Tiled online-softmax attention over (B, S, H, D).

    Drop-in for ``dot_product_attention`` (zero offsets); differentiable
    via the fused FlashAttention-2 backward. Requires sequence lengths
    ``_pick_blocks`` can tile (a divisor >= 128 that is a multiple of
    16) and a head_dim multiple of 64 (``flash_supported``); tile sizes
    scale up with S from the static menu unless an autotuned record
    (``bigdl_tpu/tuning``) overrides them.
    """
    o, _ = flash_attention_with_lse(q, k, v, causal=causal, scale=scale,
                                    interpret=interpret)
    return o


def flash_attention_with_lse(q, k, v, *, causal: bool = False,
                             scale: float | None = None,
                             interpret: bool = False):
    """``flash_attention`` that also returns the per-row logsumexp
    (B, S, H) of the scaled scores — the statistic blockwise/ring
    attention needs to merge partial results across sequence shards
    (parallel/sequence.py). Both outputs are differentiable.
    """
    b, sq, h, d = q.shape
    scale = scale if scale is not None else d ** -0.5

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    o, lse = _flash_bhsd(fold(q), fold(k), fold(v), scale, causal,
                         interpret)
    o = o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    lse = lse.reshape(b, h, sq).transpose(0, 2, 1)
    return o, lse

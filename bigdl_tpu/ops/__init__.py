"""TPU-specific op implementations (Pallas kernels and shared numeric
rewrites) used by the nn layer library where profiling justified them."""


def pow_neg_beta(s, beta):
    """s**(-beta) without transcendentals for the betas the model zoo uses.

    ``pow`` lowers to exp/log on TPU; LRN's universal beta=0.75 is
    rsqrt(s)*sqrt(rsqrt(s)) — pure VPU sqrt ops.
    """
    import jax
    import jax.numpy as jnp
    if beta == 0.75:
        r = jax.lax.rsqrt(s)
        return r * jnp.sqrt(r)
    if beta == 0.5:
        return jax.lax.rsqrt(s)
    if beta == 1.0:
        return 1.0 / s
    return jnp.power(s, -beta)

"""Tensor layer: dtype policy and pytree/flat-parameter helpers.

The reference implements a full Torch-semantics tensor library
(tensor/Tensor.scala:35, tensor/TensorMath.scala:38-707, 6.5k LoC) dispatching
to MKL via JNI. On TPU the tensor layer *is* ``jax.numpy`` on device arrays —
XLA owns layout, fusion and parallelism — so this package only provides what
JAX does not: the numeric dtype policy (the reference's ``TensorNumeric``
typeclass seam, tensor/TensorNumeric.scala:26-525) and the flat-parameter
view used by optimizers and checkpoints (the reference's ``Module.flatten``,
nn/Module.scala:41-69).
"""
from __future__ import annotations

import contextlib
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "DTypePolicy", "get_policy", "set_policy", "policy_scope",
    "default_dtype", "compute_dtype", "activation_dtype",
    "flatten_params", "unflatten_params", "tree_size", "tree_zeros_like",
]


@dataclass(frozen=True)
class DTypePolicy:
    """Numeric dispatch seam: parameter dtype vs on-MXU compute dtype.

    Mirrors the reference's NumericFloat/NumericDouble instances
    (tensor/TensorNumeric.scala:142,332) but TPU-first: the interesting axis
    on TPU is f32 params with bf16 matmul/conv compute.
    """
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    # dtype activations are *materialized* in between layers. None means
    # param_dtype (full precision everywhere). Setting bfloat16 halves the
    # HBM traffic of every activation and residual saved for backward — on
    # TPU the training step is bandwidth-bound, so this is the single
    # biggest throughput lever (measured 28.9 GB -> ~15 GB per Inception
    # step). Normalization statistics and softmax stay f32 internally.
    activation_dtype: jnp.dtype | None = None


_policy = DTypePolicy()


def get_policy() -> DTypePolicy:
    return _policy


def set_policy(policy: DTypePolicy) -> None:
    global _policy
    _policy = policy


@contextlib.contextmanager
def policy_scope(policy: DTypePolicy):
    prev = get_policy()
    set_policy(policy)
    try:
        yield
    finally:
        set_policy(prev)


def default_dtype() -> jnp.dtype:
    return _policy.param_dtype


def compute_dtype() -> jnp.dtype:
    return _policy.compute_dtype


def activation_dtype() -> jnp.dtype:
    """Dtype layer outputs are cast to (what lives in HBM between ops)."""
    return (_policy.activation_dtype if _policy.activation_dtype is not None
            else _policy.param_dtype)


# ---------------------------------------------------------------------------
# Flat parameter view (reference: Module.flatten, nn/Module.scala:41-69).
# The reference physically compacts all layer weights into ONE contiguous
# storage so whole-model allreduce and Torch-style optimizers work on a single
# vector. In JAX the native representation is the params pytree; the flat view
# is materialized only at the seams that want it (LBFGS, checkpoints of the
# reference's layout, parity adapters).
# ---------------------------------------------------------------------------

def flatten_params(tree):
    """Concatenate all leaves of a params pytree into one 1-D vector.

    Returns ``(flat, unravel)`` where ``unravel(flat) -> tree``.
    """
    leaves, treedef = jax.tree.flatten(tree)
    shapes = [l.shape for l in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    dtypes = [l.dtype for l in leaves]
    if leaves:
        flat = jnp.concatenate([jnp.ravel(l).astype(jnp.result_type(*dtypes))
                                for l in leaves])
    else:
        flat = jnp.zeros((0,), default_dtype())

    def unravel(vec):
        out, off = [], 0
        for shape, size, dt in zip(shapes, sizes, dtypes):
            out.append(jnp.reshape(vec[off:off + size], shape).astype(dt))
            off += size
        return jax.tree.unflatten(treedef, out)

    return flat, unravel


def tree_size(tree) -> int:
    """Total number of scalar parameters in a pytree."""
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(tree))


def tree_zeros_like(tree):
    return jax.tree.map(jnp.zeros_like, tree)


def unflatten_params(vec, like_tree):
    _, unravel = flatten_params(like_tree)
    return unravel(vec)

"""Span tracer exporting Chrome trace-event JSON (Perfetto-loadable).

``trace.span("device step")`` wraps a HOST phase in a complete ("X")
trace event; :meth:`Tracer.export` writes the standard
``{"traceEvents": [...]}`` JSON that chrome://tracing and
https://ui.perfetto.dev open directly (SURVEY §2.7 per-module timing
hooks, rebuilt for the XLA era where per-op host timers cannot see
inside a compiled step).

THE NO-SYNC CONTRACT. Spans read ``time.monotonic()`` and append to a
host list — nothing else. They must wrap code OUTSIDE jitted functions
(dispatch, host input, readback); they never call ``block_until_ready``
and never make a span boundary force one. Where the surrounding loop
*intentionally* blocks on a device value (the optimizers' packed loss
drain, ``np.asarray(tokens)``), pass ``host_sync="why"`` to
:meth:`span` or call :meth:`host_sync` so the sync is EXPLICIT in the
trace instead of an invisible stall. dev/lint.py enforces that this
package never imports jax at module top level.

A process-wide tracer (disabled by default — disabled spans are a
single attribute check) sits behind module-level ``span`` / ``instant``
/ ``counter`` / ``enable`` / ``export`` so call sites just do::

    from bigdl_tpu.observability import trace
    with trace.span("loss drain", host_sync="packed loss readback"):
        ...
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time

__all__ = ["Tracer", "get_tracer", "set_tracer", "enable", "disable",
           "enabled", "span", "instant", "counter", "host_sync",
           "export", "to_dict", "clear"]


class Tracer:
    """Thread-safe event buffer on monotonic clocks. ``ts`` is
    microseconds since tracer creation; ``pid``/``tid`` identify the
    emitting process/thread; the buffer is bounded (drops counted, not
    grown) so an unattended server can leave tracing on."""

    def __init__(self, max_events: int = 1_000_000,
                 enabled: bool = False):
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._dropped = 0
        self._max = max_events
        self._enabled = bool(enabled)
        self._t0 = time.monotonic()
        self._pid = os.getpid()
        # taps see every event even while export-tracing is off — the
        # flight recorder's ring buffer rides here (flight_recorder.py)
        self._taps: list = []

    # -- lifecycle --
    def enable(self):
        self._enabled = True
        return self

    def disable(self):
        self._enabled = False
        return self

    @property
    def enabled(self) -> bool:
        return self._enabled

    def clear(self):
        with self._lock:
            self._events = []
            self._dropped = 0
        return self

    # -- taps (flight recorder et al.) --
    def add_tap(self, fn) -> None:
        """Subscribe ``fn(event_dict)`` to every span/instant/counter
        event, INDEPENDENT of the enabled flag — a disabled tracer with
        a tap still builds events (but buffers nothing). Tap errors are
        swallowed: observability must never take down the loop."""
        with self._lock:
            if fn not in self._taps:
                self._taps.append(fn)

    def remove_tap(self, fn) -> None:
        with self._lock:
            if fn in self._taps:
                self._taps.remove(fn)

    # -- recording --
    def _now_us(self) -> float:
        return (time.monotonic() - self._t0) * 1e6

    def _emit(self, ev: dict) -> None:
        for tap in list(self._taps):
            try:
                tap(ev)
            except Exception:
                pass
        if not self._enabled:
            return
        with self._lock:
            if len(self._events) >= self._max:
                self._dropped += 1
                return
            self._events.append(ev)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host", **args):
        """Complete-event context manager. Extra kwargs land in the
        event's ``args`` (use ``host_sync="why"`` to mark that the
        wrapped code intentionally blocks on a device value)."""
        if not self._enabled and not self._taps:
            yield
            return
        t0 = self._now_us()
        try:
            yield
        finally:
            t1 = self._now_us()
            ev = {"name": name, "cat": cat, "ph": "X", "ts": t0,
                  "dur": t1 - t0, "pid": self._pid,
                  "tid": threading.get_ident()}
            if args:
                ev["args"] = args
            self._emit(ev)

    def instant(self, name: str, cat: str = "host", **args):
        if not self._enabled and not self._taps:
            return
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": self._now_us(), "pid": self._pid,
              "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._emit(ev)

    def counter(self, name: str, value: float, cat: str = "host"):
        """Counter-track event (renders as a value-over-time track)."""
        if not self._enabled and not self._taps:
            return
        self._emit({"name": name, "cat": cat, "ph": "C",
                    "ts": self._now_us(), "pid": self._pid,
                    "tid": threading.get_ident(),
                    "args": {"value": float(value)}})

    def host_sync(self, what: str, **args):
        """Annotate an INTENTIONAL host<-device sync point (loss
        readback, token fetch). Also counts into the default registry's
        ``trace_host_syncs_total`` so a sync added to a hot loop shows
        up in metrics even with tracing off."""
        from bigdl_tpu.observability.registry import default_registry
        default_registry().counter(
            "trace_host_syncs_total",
            "intentional host<-device sync annotations").inc()
        self.instant(what, cat="host_sync", **args)

    # -- export --
    def to_dict(self, last: int | None = None) -> dict:
        """Chrome trace JSON. ``last=N`` keeps only the N most recent
        events (the exporter's ``/trace?last=`` cap — a live scrape of
        a long run must not ship the whole 1M-event ring); elided
        events are reported in ``otherData.elided_events``."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        elided = 0
        if last is not None and len(events) > max(int(last), 0):
            elided = len(events) - max(int(last), 0)
            events = events[elided:]
        out = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"dropped_events": dropped,
                             "clock": "monotonic_us"}}
        if elided:
            out["otherData"]["elided_events"] = elided
        return out

    def export(self, path: str) -> str:
        """Write Chrome trace JSON; open in chrome://tracing or
        ui.perfetto.dev. Parent directories are created (a postmortem
        dump must not fail on a fresh run dir). Returns ``path``."""
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f)
        return path


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def set_tracer(tracer: Tracer) -> Tracer:
    global _TRACER
    _TRACER = tracer
    return tracer


def enable():
    return _TRACER.enable()


def disable():
    return _TRACER.disable()


def enabled() -> bool:
    return _TRACER.enabled


def span(name: str, cat: str = "host", **args):
    return _TRACER.span(name, cat=cat, **args)


def instant(name: str, cat: str = "host", **args):
    return _TRACER.instant(name, cat=cat, **args)


def counter(name: str, value: float, cat: str = "host"):
    return _TRACER.counter(name, value, cat=cat)


def host_sync(what: str, **args):
    return _TRACER.host_sync(what, **args)


def export(path: str) -> str:
    return _TRACER.export(path)


def to_dict(last: int | None = None) -> dict:
    return _TRACER.to_dict(last=last)


def clear():
    return _TRACER.clear()

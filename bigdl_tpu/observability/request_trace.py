"""Per-request timelines: end-to-end tail-latency attribution.

Aggregate histograms say the TTFT p99 breached; they cannot say where
*that request's* time went. This module records one structured timeline
per request across the whole serving path — router admission, pending
park, prefix-cache outcome, placement, disaggregated handoff, batcher
prefill (with compile events via a ``compile_watch`` tap), per-burst
decode with stall detection, drain/migrate/requeue/orphan-restart, and
retirement — so a slow request explains itself (docs/OBSERVABILITY.md
"Request timelines"; the production-serving identity of the reference,
arXiv:1804.05839, arXiv:2204.01715).

Two classes:

- :class:`RequestTimeline` — a bounded, thread-safe, monotonically
  timestamped event list for ONE request. ``record()`` is a lock +
  list append; attribution components (queue / prefill / decode /
  stall / migration seconds) accumulate incrementally on recognized
  event names, so a timeline that overflows its event bound keeps
  exact attribution anyway (overflow drops events, never seconds).
- :class:`RequestTracker` — the fleet-wide ledger with TAIL SAMPLING:
  every in-flight request gets a full timeline (a crash dump must
  explain its victims), but at retirement only the interesting tail is
  retained in full — every SLO-violating or abnormally finished
  request, the slowest-K of a rolling window, plus a deterministic
  1-in-N sample of the fast majority. Everything else is dropped after
  its seconds landed in the aggregate histograms (the router's
  ``router_queue_wait_seconds`` is observed for EVERY request,
  independent of sampling).

Surfaces: ``MetricsServer`` ``/requests`` (slowest-K summaries) and
``/requests/<id>`` (full timeline JSON); ``FlightRecorder``
postmortems write ``requests.jsonl``; ``Router.latency_summary()``
carries :meth:`RequestTracker.attribution`.

Locking: the tracker lock is a strict LEAF — it guards only the
tracker's own dicts and is never held across a call into any other
component; timelines carry their own leaf lock and never call out at
all. Holding either while acquiring a serving-plane lock is a
raceguard TS1 failure (declarations below; the sanctioned nesting is
the reverse — router/replica threads record events while holding
their own locks).

HOST-ONLY CONTRACT: never imports jax (jaxlint JX5); recording is a
lock + dict/list update on host memory, safe at decode-burst
frequency.
"""
# raceguard: order requesttracker.mu < state_lock < replica.lock
# raceguard: order requesttimeline.mu < requesttracker.mu
from __future__ import annotations

import threading
import time
from collections import deque

__all__ = ["RequestTimeline", "RequestTracker", "default_tracker",
           "COMPONENTS"]

# the attribution decomposition every timeline accumulates; summaries
# and Router.latency_summary()["attribution"] key on these names
COMPONENTS = ("queue_s", "prefill_s", "decode_s", "stall_s",
              "migration_s")

# events appended even when the timeline is at its event bound: losing
# the terminal record would make a bounded timeline look in-flight
_ALWAYS_KEEP = ("finish", "retire", "complete")


class RequestTimeline:
    """Bounded structured event list for one request (see module
    docstring). Events are ``{"t": <monotonic seconds>, "event":
    <name>, ...fields}``; ``t`` shares one clock across every emitting
    thread, so router- and batcher-side events interleave in causal
    order."""

    def __init__(self, request_id, *, max_events: int = 256):
        self.request_id = request_id
        self._mu = threading.Lock()
        self._max = int(max_events)
        self._events: list[dict] = []
        self._dropped = 0
        self._t0 = time.monotonic()
        self._t_first_token: float | None = None
        self._t_finish: float | None = None
        self._status: str | None = None
        self._tokens = 0
        self._replicas: list = []
        self._versions: list = []
        self._components = dict.fromkeys(COMPONENTS, 0.0)
        self.retained_reason: str | None = None

    # -- recording --
    def record(self, event: str, **fields) -> None:
        """Append one event. Attribution components update even when
        the event itself is dropped by the bound."""
        t = time.monotonic()
        with self._mu:
            self._absorb(event, t, fields)
            if len(self._events) >= self._max and \
                    event not in _ALWAYS_KEEP:
                self._dropped += 1
                return
            ev = {"t": round(t - self._t0, 9), "event": event}
            ev.update(fields)
            self._events.append(ev)

    def _absorb(self, event: str, t: float, fields: dict) -> None:
        """Component/identity accumulation (called under ``_mu``)."""
        c = self._components
        if event == "place":
            wait = float(fields.get("wait_s") or 0.0)
            if fields.get("cause") == "submit":
                c["queue_s"] += wait
            else:                   # requeue / restart re-placements
                c["migration_s"] += wait
        elif event in ("prefill_end", "adopt"):
            c["prefill_s"] += float(fields.get("dur_s") or 0.0)
            c["queue_s"] += float(fields.get("queue_s") or 0.0)
        elif event == "decode":
            c["decode_s"] += float(fields.get("dur_s") or 0.0)
            c["stall_s"] += float(fields.get("stall_s") or 0.0)
        elif event == "export":
            c["migration_s"] += float(fields.get("dur_s") or 0.0)
        elif event == "first_token":
            if self._t_first_token is None:
                self._t_first_token = t
        elif event == "finish":
            self._t_finish = t
            self._status = str(fields.get("status", "ok"))
        if event in ("retire", "complete"):
            n = fields.get("tokens")
            if n is not None:
                self._tokens = max(self._tokens, int(n))
        rep = fields.get("replica")
        if rep is not None and rep not in self._replicas:
            self._replicas.append(rep)
        ver = fields.get("weight_version")
        if ver is not None and ver not in self._versions:
            self._versions.append(ver)

    # -- views --
    @property
    def finished(self) -> bool:
        return self._t_finish is not None

    @property
    def duration_s(self) -> float:
        end = self._t_finish
        return (time.monotonic() if end is None else end) - self._t0

    @property
    def ttft_s(self) -> float | None:
        t = self._t_first_token
        return None if t is None else t - self._t0

    @property
    def stalled(self) -> bool:
        return self._components["stall_s"] > 0.0

    def summary(self) -> dict:
        """Compact per-request record (/requests rows)."""
        with self._mu:
            return {
                "request_id": str(self.request_id),
                "status": self._status or "in_flight",
                "duration_s": self.duration_s,
                "ttft_s": self.ttft_s,
                "tokens": self._tokens,
                "replicas": list(self._replicas),
                "weight_versions": list(self._versions),
                "components": dict(self._components),
                "events": len(self._events),
                "dropped_events": self._dropped,
                "retained_reason": self.retained_reason,
            }

    def to_dict(self) -> dict:
        """Full timeline (summary + every retained event)."""
        with self._mu:
            events = [dict(e) for e in self._events]
        out = self.summary()
        out["timeline"] = events
        return out


class RequestTracker:
    """Fleet-wide request ledger with tail sampling (module
    docstring). One process-wide instance lives behind
    :func:`default_tracker`; components take ``tracker=`` to isolate.

    Retention policy, decided at :meth:`finish` time:

    - ``slo``      — TTFT over ``slo.ttft_p99_s``, any decode stall,
      or a non-``"ok"`` status (shed / cancelled / failed): ALWAYS
      retained.
    - ``slowest``  — the request ranks in the slowest ``slowest_k`` of
      the last ``window`` finished durations: retained.
    - ``sampled``  — deterministic 1-in-``sample_every`` counter
      sample of everything else (no RNG; reproducible in tests).

    The retained ring is bounded (``max_retained``); the oldest tail
    entries fall off first.
    """

    def __init__(self, *, slo=None, sample_every: int = 16,
                 slowest_k: int = 8, window: int = 128,
                 max_retained: int = 256, max_events: int = 256,
                 stall_factor: float = 4.0):
        if int(sample_every) < 1:
            raise ValueError(f"sample_every must be >= 1, got "
                             f"{sample_every}")
        self.slo = slo
        self.sample_every = int(sample_every)
        self.slowest_k = int(slowest_k)
        self.max_events = int(max_events)
        self._stall_factor = float(stall_factor)
        self._mu = threading.Lock()
        self._live: dict = {}                 # rid -> RequestTimeline
        self._retained: deque = deque(maxlen=int(max_retained))
        self._window: deque = deque(maxlen=int(window))
        self._started = 0
        self._finished = 0
        self._sample_count = 0
        self._retained_by: dict[str, int] = {"slo": 0, "slowest": 0,
                                             "sampled": 0}

    # -- thresholds the batcher reads (host-side, lock-free) --
    @property
    def ttft_slo_s(self) -> float:
        return float(self.slo.ttft_p99_s) if self.slo is not None \
            else float("inf")

    @property
    def stall_threshold_s(self) -> float:
        """Per-token decode latency past which a burst counts as a
        stall: ``stall_factor`` x the SLO per-token target (a stall is
        a pathological burst, not a p99 grazer)."""
        if self.slo is None:
            return float("inf")
        return self._stall_factor * float(self.slo.decode_token_p99_s)

    # -- recording --
    def begin(self, request_id, **fields) -> RequestTimeline:
        """Open (or return the already-open) timeline for
        ``request_id`` and record its ``submit`` event. Idempotent:
        a requeued/migrated request keeps its ONE timeline."""
        with self._mu:
            tl = self._live.get(request_id)
            fresh = tl is None
            if fresh:
                tl = RequestTimeline(request_id,
                                     max_events=self.max_events)
                self._live[request_id] = tl
                self._started += 1
        if fresh:
            tl.record("submit", **fields)
        return tl

    def event(self, request_id, event: str, **fields) -> bool:
        """Record one event onto the live timeline; False (dropped)
        for unknown/already-finished ids."""
        with self._mu:
            tl = self._live.get(request_id)
        if tl is None:
            return False
        tl.record(event, **fields)
        return True

    def finish(self, request_id, *, status: str = "ok") -> dict | None:
        """Seal the timeline, decide retention, return its summary
        (None for unknown ids). Exactly-once: the first finish wins;
        later calls are no-ops."""
        with self._mu:
            tl = self._live.pop(request_id, None)
        if tl is None:
            return None
        tl.record("finish", status=status)
        dur = tl.duration_s
        ttft = tl.ttft_s
        slo_violated = (status != "ok" or tl.stalled
                        or (ttft is not None
                            and ttft > self.ttft_slo_s))
        with self._mu:
            self._finished += 1
            window = sorted(self._window, reverse=True)
            kth = window[self.slowest_k - 1] \
                if len(window) >= self.slowest_k else 0.0
            self._window.append(dur)
            reason = None
            if slo_violated:
                reason = "slo"
            elif dur >= kth or len(window) < self.slowest_k:
                reason = "slowest"
            else:
                self._sample_count += 1
                if self._sample_count % self.sample_every == 0:
                    reason = "sampled"
            if reason is not None:
                tl.retained_reason = reason
                self._retained_by[reason] += 1
                self._retained.append(tl)
        return tl.summary()

    # -- views --
    def inflight(self) -> list[dict]:
        with self._mu:
            live = list(self._live.values())
        return [tl.summary() for tl in live]

    def retained(self) -> list["RequestTimeline"]:
        with self._mu:
            return list(self._retained)

    def slowest(self, k: int = 32) -> list[dict]:
        """Slowest-k retained summaries, slowest first (the
        ``/requests`` body)."""
        out = [tl.summary() for tl in self.retained()]
        out.sort(key=lambda s: s["duration_s"], reverse=True)
        return out[:max(int(k), 0)]

    def timeline(self, request_id) -> dict | None:
        """Full timeline for a live or retained id (``/requests/<id>``;
        retained ids may repeat — the newest wins)."""
        rid = str(request_id)
        with self._mu:
            tl = self._live.get(request_id)
            if tl is None:        # ids over HTTP arrive as strings
                for cand in self._live.values():
                    if str(cand.request_id) == rid:
                        tl = cand
                        break
            if tl is None:
                for cand in reversed(self._retained):
                    if str(cand.request_id) == rid:
                        tl = cand
                        break
        return None if tl is None else tl.to_dict()

    def attribution(self) -> dict:
        """Where the tail's time went: the retained requests at or
        above the p99 duration (always at least the slowest one)
        decomposed into mean per-request component seconds and
        fractions. Components need not sum to the duration (untracked
        time shows up as a fraction gap, which is itself a signal)."""
        tails = self.retained()
        if not tails:
            return {"requests": 0, "tail_requests": 0,
                    "p99_duration_s": None, "components": {},
                    "fractions": {}}
        durs = sorted(tl.duration_s for tl in tails)
        p99 = durs[max(0, min(len(durs) - 1,
                              int(round(0.99 * (len(durs) - 1)))))]
        tail = [tl for tl in tails if tl.duration_s >= p99] or \
            [max(tails, key=lambda tl: tl.duration_s)]
        comp = dict.fromkeys(COMPONENTS, 0.0)
        total = 0.0
        for tl in tail:
            s = tl.summary()
            total += s["duration_s"]
            for k in COMPONENTS:
                comp[k] += s["components"][k]
        n = len(tail)
        return {
            "requests": len(tails),
            "tail_requests": n,
            "p99_duration_s": p99,
            "components": {k: v / n for k, v in comp.items()},
            "fractions": {k: (v / total if total > 0 else 0.0)
                          for k, v in comp.items()},
        }

    def stats(self) -> dict:
        with self._mu:
            return {
                "started": self._started,
                "finished": self._finished,
                "in_flight": len(self._live),
                "retained": len(self._retained),
                "retained_by": dict(self._retained_by),
                "sampled_out": (self._sample_count
                                - self._retained_by["sampled"]),
            }

    def to_records(self) -> list[dict]:
        """Full timelines for postmortems (``requests.jsonl``):
        in-flight first (the crash's victims), then the retained tail,
        newest last."""
        with self._mu:
            live = list(self._live.values())
            kept = list(self._retained)
        return [tl.to_dict() for tl in live] + \
            [tl.to_dict() for tl in kept]


_DEFAULT = RequestTracker()


def default_tracker() -> RequestTracker:
    """The process-wide tracker (pass ``tracker=`` to instrumented
    components to isolate — tests construct their own)."""
    return _DEFAULT

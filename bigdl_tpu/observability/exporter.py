"""HTTP exposition: /metrics, /metrics.json, /trace, /healthz, /readyz.

The telemetry plane's front door. PR 1 gave every subsystem a
process-wide metric registry and a span tracer, but only the owning
process could read them; a long-running ``ContinuousBatcher`` or a
multi-hour ``DistriOptimizer`` run had no scrape target and no health
probe. BigDL's operating premise was that training jobs run as ordinary
cluster citizens with standard operational tooling (arXiv:1804.05839;
BigDL 2.0's production-pipeline doubling-down, arXiv:2204.01715) — on a
JAX/TPU stack that means a Prometheus endpoint and k8s-style
liveness/readiness probes, served by the stdlib so serving images stay
dependency-free.

Endpoints (GET):

- ``/metrics``        Prometheus text exposition of the registry.
- ``/metrics.json``   the registry's ``dump()`` as JSON.
- ``/trace``          Chrome trace JSON from the live tracer (open the
  response body in ui.perfetto.dev). Capped to the most recent
  ``DEFAULT_TRACE_LAST`` events; ``?last=N`` overrides (``0`` = all).
- ``/requests``       slowest-K retained request timelines (summaries)
  plus in-flight requests, from the :class:`RequestTracker`
  (``?k=N`` picks K; docs/OBSERVABILITY.md "Request timelines").
- ``/requests/<id>``  ONE request's full timeline JSON (404 unknown).
- ``/healthz``        liveness checks (process up + registered
  ``kind="liveness"`` checks) — 200 ok / 503 failing, JSON body.
- ``/readyz``         readiness checks (``kind="readiness"``) — the
  load-balancer gate. A batcher that cannot admit reports not-ready.
  Both probe endpoints accept ``?check=NAME[,NAME...]`` to gate on a
  subset — the per-replica /readyz when one process hosts N serving
  replicas (``serving_replica_<name>`` checks, docs/SERVING.md).

Health checks are pluggable: ``default_health().register(name, fn,
kind=...)`` where ``fn() -> (ok, detail)``. The optimizers register a
training-liveness check (step progressed within a deadline); the
continuous batcher registers serving readiness (admitting).

HOST-ONLY CONTRACT: never imports jax (jaxlint JX5); every handler
reads host state under locks. Serving a scrape can never add a device
sync or a compile. The server is opt-in, binds ``127.0.0.1`` by
default, supports port 0 (ephemeral — read ``server.port``), and runs
daemon threads only, so it can never hold a training process alive.
"""
from __future__ import annotations

import json
import threading

__all__ = ["HealthCheck", "HealthRegistry", "default_health",
           "MetricsServer", "DEFAULT_TRACE_LAST"]

# /trace ships at most this many (most recent) tracer events unless
# ?last= overrides — the ring defaults to 1M events and a live scrape
# of a long run must stay bounded (?last=0 means "everything")
DEFAULT_TRACE_LAST = 10_000


class HealthCheck:
    """One named probe: ``fn() -> (ok, detail)`` (a bare bool is also
    accepted). ``kind`` is ``"liveness"`` (/healthz) or ``"readiness"``
    (/readyz)."""

    KINDS = ("liveness", "readiness")

    def __init__(self, name: str, fn, kind: str = "readiness"):
        if kind not in self.KINDS:
            raise ValueError(f"health check kind must be one of "
                             f"{self.KINDS}, got {kind!r}")
        self.name = str(name)
        self.fn = fn
        self.kind = kind

    def run(self) -> tuple[bool, str]:
        """Never raises: a crashing probe reports itself as failing."""
        try:
            out = self.fn()
        except Exception as e:
            return False, f"check raised {type(e).__name__}: {e}"
        if isinstance(out, tuple):
            ok, detail = out
            return bool(ok), str(detail)
        return bool(out), ""


class HealthRegistry:
    """Name -> check map. Re-registering a name replaces the old check
    (a restarted batcher takes over its probe); ``unregister`` on
    shutdown so a dead component stops answering for the process."""

    def __init__(self):
        self._lock = threading.Lock()
        self._checks: dict[str, HealthCheck] = {}

    def register(self, name: str, fn, *,
                 kind: str = "readiness") -> HealthCheck:
        check = HealthCheck(name, fn, kind)
        with self._lock:
            self._checks[name] = check
        return check

    def unregister(self, name: str) -> None:
        with self._lock:
            self._checks.pop(name, None)

    def checks(self, kind: str | None = None) -> list[HealthCheck]:
        with self._lock:
            out = [self._checks[n] for n in sorted(self._checks)]
        if kind is not None:
            out = [c for c in out if c.kind == kind]
        return out

    def run(self, kind: str, names=None) -> tuple[bool, dict]:
        """Run every check of ``kind`` (optionally restricted to
        ``names`` — the per-replica /readyz gate: one process serving N
        batcher replicas answers for each one separately). With none
        registered the verdict is ok — an empty process that answers
        HTTP is alive, and ready-by-default matches a component-free
        harness. A requested name with no registered check reports
        failing: a load balancer probing a replica that never came up
        must not route to it."""
        results = {}
        ok = True
        checks = self.checks(kind)
        if names is not None:
            want = set(names)
            by_name = {c.name: c for c in checks}
            checks = []
            for n in sorted(want):
                c = by_name.get(n)
                if c is None:
                    ok = False
                    results[n] = {"ok": False,
                                  "detail": "no such check registered"}
                else:
                    checks.append(c)
        for c in checks:
            c_ok, detail = c.run()
            ok = ok and c_ok
            results[c.name] = {"ok": c_ok, "detail": detail}
        return ok, results


_DEFAULT_HEALTH = HealthRegistry()


def default_health() -> HealthRegistry:
    """The process-wide health registry the default server exposes
    (components take ``health=`` to isolate, like ``registry=``)."""
    return _DEFAULT_HEALTH


class MetricsServer:
    """Opt-in ``ThreadingHTTPServer`` over the live registry/tracer.

    ``port=0`` binds an ephemeral port (read it back from ``.port``).
    ``start()`` returns self; ``close()`` shuts down and joins — no
    non-daemon threads survive it (test-pinned). Usable as a context
    manager. One scrape surface shows training, serving and bench
    series side by side because everything defaults to the process-wide
    registry.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1", *,
                 registry=None, tracer=None, health=None, tracker=None):
        if registry is None:
            from bigdl_tpu.observability.registry import default_registry
            registry = default_registry()
        if tracer is None:
            from bigdl_tpu.observability.tracing import get_tracer
            tracer = get_tracer()
        if tracker is None:
            from bigdl_tpu.observability.request_trace import \
                default_tracker
            tracker = default_tracker()
        self.registry = registry
        self.tracer = tracer
        self.tracker = tracker
        self.health = health if health is not None else default_health()
        self._host = host
        self._want_port = int(port)
        self._httpd = None
        self._thread = None

    # -- lifecycle --
    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        self._httpd = _make_server(self._host, self._want_port, self)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="bigdl-metrics-server", daemon=True)
        self._thread.start()
        return self

    @property
    def port(self) -> int | None:
        return None if self._httpd is None else \
            self._httpd.server_address[1]

    @property
    def url(self) -> str | None:
        return None if self._httpd is None else \
            f"http://{self._host}:{self.port}"

    def close(self) -> None:
        httpd, thread = self._httpd, self._thread
        self._httpd = self._thread = None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # -- endpoint bodies (handler-independent, unit-testable) --
    def render(self, path: str) -> tuple[int, str, bytes]:
        """(status, content_type, body) for a request path."""
        path, _, query = path.partition("?")
        if path == "/metrics":
            return (200, "text/plain; version=0.0.4; charset=utf-8",
                    self.registry.expose().encode("utf-8"))
        if path == "/metrics.json":
            return (200, "application/json",
                    self.registry.dump_json().encode("utf-8"))
        if path == "/trace":
            last = DEFAULT_TRACE_LAST
            if query:
                from urllib.parse import parse_qs
                raw = parse_qs(query).get("last", [""])[-1]
                try:
                    last = int(raw)
                except ValueError:
                    pass
            # ?last=0 (or negative) lifts the cap: the postmortem-style
            # full dump, explicitly requested
            cap = last if last > 0 else None
            return (200, "application/json",
                    json.dumps(self.tracer.to_dict(last=cap))
                    .encode("utf-8"))
        if path == "/requests":
            # slowest-K retained timelines (summaries), plus what is
            # in flight right now and the tracker's sampling counters
            k = 32
            if query:
                from urllib.parse import parse_qs
                raw = parse_qs(query).get("k", [""])[-1]
                try:
                    k = int(raw)
                except ValueError:
                    pass
            body = json.dumps(
                {"slowest": self.tracker.slowest(k),
                 "in_flight": self.tracker.inflight(),
                 "stats": self.tracker.stats()},
                sort_keys=True, default=repr).encode("utf-8")
            return 200, "application/json", body
        if path.startswith("/requests/"):
            rid = path[len("/requests/"):]
            tl = self.tracker.timeline(rid)
            if tl is None:
                return (404, "application/json",
                        json.dumps({"error": "unknown request id",
                                    "request_id": rid})
                        .encode("utf-8"))
            return (200, "application/json",
                    json.dumps(tl, sort_keys=True, default=repr)
                    .encode("utf-8"))
        if path in ("/healthz", "/readyz"):
            kind = "liveness" if path == "/healthz" else "readiness"
            # ?check=NAME[,NAME...] (repeatable) narrows the verdict to
            # the named checks — the per-replica LB gate when one
            # process hosts N serving replicas (docs/SERVING.md)
            names = None
            if query:
                from urllib.parse import parse_qs
                picked = [n for v in parse_qs(query).get("check", [])
                          for n in v.split(",") if n]
                names = picked or None
            ok, results = self.health.run(kind, names)
            body = json.dumps({"status": "ok" if ok else "failing",
                               "kind": kind, "checks": results},
                              sort_keys=True).encode("utf-8")
            return (200 if ok else 503, "application/json", body)
        if path in ("/", ""):
            body = ("bigdl_tpu telemetry plane\n"
                    "endpoints: /metrics /metrics.json /trace "
                    "/requests /requests/<id> "
                    "/healthz /readyz\n").encode("utf-8")
            return 200, "text/plain; charset=utf-8", body
        return (404, "text/plain; charset=utf-8",
                f"unknown path {path!r}\n".encode("utf-8"))


def _make_server(host: str, port: int, owner: MetricsServer):
    # stdlib imports live here so importing this module costs nothing
    # in processes that never serve
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        server_version = "bigdl-tpu-metrics/1.0"

        def do_GET(self):          # noqa: N802 (stdlib API)
            try:
                status, ctype, body = owner.render(self.path)
            except Exception as e:   # a scrape must never crash serving
                status, ctype = 500, "text/plain; charset=utf-8"
                body = f"exporter error: {type(e).__name__}: {e}\n" \
                    .encode("utf-8")
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            import logging
            logging.getLogger("bigdl_tpu.observability.exporter").debug(
                "%s - %s", self.address_string(), fmt % args)

    httpd = ThreadingHTTPServer((host, port), Handler)
    httpd.daemon_threads = True
    return httpd

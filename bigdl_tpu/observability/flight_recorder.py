"""Crash flight recorder: a bounded black box + postmortem dumps.

A multi-hour ``DistriOptimizer`` run or a long-lived serving process
that dies at 3am leaves nothing behind unless someone was tailing logs.
The flight recorder keeps a BOUNDED ring of the most recent telemetry
events (trace spans/instants via a tracer tap, warning-level-and-up log
records via a logging handler, plus anything recorded explicitly) and,
on abnormal exit, writes a self-contained postmortem directory:

    postmortem/
      exception.json       what killed it (type, message, traceback)
      registry.json        full metric-registry dump at death
      trace.json           the live tracer buffer (Chrome trace JSON)
      events.jsonl         the ring: last-N spans/instants/log records
      compile_watch.json   the compile ledger (recompile-storm evidence)
      requests.jsonl       per-request timelines: in-flight first (the
                           crash's victims), then the retained tail
                           (observability/request_trace.py)

``install()`` arms process-level hooks — ``sys.excepthook`` (chained),
``SIGTERM`` (main thread only; the k8s eviction signal), and an
``atexit`` backstop that dumps if an error was observed but never
dumped — so even a crash outside any try/except leaves the black box.
The optimizers additionally dump EXPLICITLY when their loop raises
(the exception may be caught upstream, where no excepthook ever fires).

Cost model: steady-state recording is a deque append per event and
nothing else — cheap enough to leave on by default in the optimizers.
All I/O happens only at dump time.

HOST-ONLY CONTRACT: never imports jax (jaxlint JX5); a dump reads host
state only and never blocks on a device value.
"""
from __future__ import annotations

import atexit
import collections
import json
import logging
import os
import sys
import threading
import time
import traceback

__all__ = ["FlightRecorder", "default_postmortem_dir"]

logger = logging.getLogger("bigdl_tpu.observability.flight_recorder")


def default_postmortem_dir() -> str:
    """``$BIGDL_TPU_POSTMORTEM_DIR`` or a per-pid tmp directory."""
    env = os.environ.get("BIGDL_TPU_POSTMORTEM_DIR")
    if env:
        return env
    import tempfile
    return os.path.join(tempfile.gettempdir(),
                        f"bigdl_tpu_postmortem_{os.getpid()}")


class _RingHandler(logging.Handler):
    """Feeds WARNING+ log records into the recorder's ring."""

    def __init__(self, recorder: "FlightRecorder"):
        super().__init__(level=logging.WARNING)
        self._recorder = recorder

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._recorder.record(
                "log", record.name, level=record.levelname,
                message=record.getMessage())
            if record.levelno >= logging.ERROR:
                self._recorder._saw_error = True
        except Exception:
            pass                    # the black box must never crash


class FlightRecorder:
    """Bounded event ring + postmortem writer.

    ``install()``/``uninstall()`` are refcounted (nested optimizers
    share one set of process hooks); a dump is once-per-reason
    idempotent so excepthook + atexit can't double-write.
    """

    def __init__(self, dir: str | None = None, max_events: int = 512,
                 *, registry=None, tracer=None, watch=None,
                 tracker=None, logger_name: str = "bigdl_tpu"):
        self.dir = dir or default_postmortem_dir()
        self._ring: collections.deque = collections.deque(
            maxlen=int(max_events))
        self._registry = registry
        self._tracer = tracer
        self._watch = watch
        self._tracker = tracker
        self._logger_name = logger_name
        self._lock = threading.Lock()
        self._installs = 0
        self._handler: _RingHandler | None = None
        self._prev_excepthook = None
        self._prev_sigterm = None
        self._saw_error = False
        self._dumped = False

    # -- dependency resolution (process-wide defaults, lazily) --
    def _get_registry(self):
        if self._registry is None:
            from bigdl_tpu.observability.registry import default_registry
            return default_registry()
        return self._registry

    def _get_tracer(self):
        if self._tracer is None:
            from bigdl_tpu.observability.tracing import get_tracer
            return get_tracer()
        return self._tracer

    def _get_watch(self):
        if self._watch is None:
            from bigdl_tpu.observability.compile_watch import default_watch
            return default_watch()
        return self._watch

    def _get_tracker(self):
        if self._tracker is None:
            from bigdl_tpu.observability.request_trace import \
                default_tracker
            return default_tracker()
        return self._tracker

    # -- recording --
    def record(self, kind: str, name: str, **fields) -> None:
        """Append one event to the ring (a deque append — safe at any
        frequency)."""
        ev = {"t": time.time(), "kind": kind, "name": name}
        if fields:
            ev.update(fields)
        self._ring.append(ev)

    def _tap(self, ev: dict) -> None:
        self.record("trace", ev.get("name", "?"),
                    ph=ev.get("ph"), cat=ev.get("cat"),
                    ts=ev.get("ts"), dur=ev.get("dur"),
                    args=ev.get("args"))

    def events(self) -> list[dict]:
        return list(self._ring)

    # -- process hooks --
    def install(self) -> "FlightRecorder":
        with self._lock:
            self._installs += 1
            if self._installs > 1:
                return self
        self._get_tracer().add_tap(self._tap)
        self._handler = _RingHandler(self)
        logging.getLogger(self._logger_name).addHandler(self._handler)
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._excepthook
        try:
            import signal
            self._prev_sigterm = signal.signal(signal.SIGTERM,
                                               self._on_sigterm)
        except ValueError:          # not the main thread
            self._prev_sigterm = None
        atexit.register(self._atexit)
        return self

    def uninstall(self) -> None:
        with self._lock:
            if self._installs == 0:
                return
            self._installs -= 1
            if self._installs > 0:
                return
        self._get_tracer().remove_tap(self._tap)
        if self._handler is not None:
            logging.getLogger(self._logger_name) \
                .removeHandler(self._handler)
            self._handler = None
        if sys.excepthook is self._excepthook:
            sys.excepthook = self._prev_excepthook
        self._prev_excepthook = None
        if self._prev_sigterm is not None:
            try:
                import signal
                if signal.getsignal(signal.SIGTERM) is self._on_sigterm:
                    signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None
        try:
            atexit.unregister(self._atexit)
        except Exception:
            pass

    @property
    def installed(self) -> bool:
        return self._installs > 0

    def __enter__(self) -> "FlightRecorder":
        return self.install()

    def __exit__(self, tp, val, tb):
        if val is not None:
            self.dump_postmortem(val, reason="context exception")
        self.uninstall()
        return False

    # -- exit paths --
    def _excepthook(self, tp, val, tb):
        try:
            self.dump_postmortem(val, reason="uncaught exception",
                                 tb=tb)
        finally:
            (self._prev_excepthook or sys.__excepthook__)(tp, val, tb)

    def _on_sigterm(self, signum, frame):
        self.dump_postmortem(None, reason="SIGTERM")
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
            return
        # default disposition: terminate with the conventional 128+15
        raise SystemExit(128 + signum)

    def _atexit(self):
        # backstop only: an ERROR-level record was seen but nothing
        # dumped (e.g. the error was logged, swallowed, and the process
        # wound down "normally")
        if self._saw_error and not self._dumped:
            self.dump_postmortem(None, reason="atexit after error")

    # -- the dump --
    def dump_postmortem(self, exc: BaseException | None = None, *,
                        reason: str = "exception", tb=None) -> str:
        """Write the postmortem directory; returns its path. Never
        raises — a broken dump logs and gives back the dir path."""
        with self._lock:
            self._dumped = True
        d = self.dir
        try:
            os.makedirs(d, exist_ok=True)
        except OSError as e:
            logger.error("flight recorder cannot create %s: %s", d, e)
            return d
        record = {"reason": reason, "time": time.time(),
                  "pid": os.getpid(),
                  "argv": list(getattr(sys, "argv", []))}
        if exc is not None:
            record["exception"] = {
                "type": type(exc).__name__, "message": str(exc),
                "traceback": "".join(traceback.format_exception(
                    type(exc), exc, tb if tb is not None
                    else exc.__traceback__)),
            }
        for fname, writer in (
                ("exception.json",
                 lambda p: _write_json(p, record)),
                ("registry.json",
                 lambda p: self._get_registry().dump_json(p)),
                ("trace.json",
                 lambda p: self._get_tracer().export(p)),
                ("events.jsonl", self._write_events),
                ("compile_watch.json",
                 lambda p: _write_json(p, self._get_watch().table())),
                ("requests.jsonl", self._write_requests)):
            try:
                writer(os.path.join(d, fname))
            except Exception as e:
                logger.error("flight recorder failed writing %s: %s",
                             fname, e)
        logger.warning("flight recorder postmortem (%s) written to %s",
                       reason, d)
        return d

    def _write_events(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            for ev in self.events():
                f.write(json.dumps(ev, default=repr) + "\n")

    def _write_requests(self, path: str) -> None:
        # in-flight timelines first (the crash's victims), then the
        # retained tail — one full timeline per line
        with open(path, "w", encoding="utf-8") as f:
            for rec in self._get_tracker().to_records():
                f.write(json.dumps(rec, default=repr) + "\n")


def _write_json(path: str, obj) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=2, sort_keys=True, default=repr)

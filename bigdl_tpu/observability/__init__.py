"""bigdl_tpu.observability — traces, metrics, and summaries.

Host-side observability spanning training and serving (reference
parity: the named per-iteration ``Metrics`` + per-module timing hooks,
SURVEY §2.7/§7, grown into the BigDL line's TrainSummary/
ValidationSummary visualization API — arXiv:1804.05839, 2204.01715).
Three pillars:

- ``registry``  — process-wide Counter/Gauge/Histogram registry with
  Prometheus text exposition and a JSON dump
  (:func:`default_registry`).
- ``trace``     — span tracer (``trace.span("device step")``) that
  exports Chrome trace-event JSON for chrome://tracing / Perfetto,
  with explicit host-sync annotations.
- ``summary``   — TrainSummary/ValidationSummary scalar event logs
  (JSONL) plus :class:`SummaryReader` for replay.

HOST-ONLY CONTRACT: nothing in this package imports jax at module top
level (dev/lint.py enforces it) and nothing here blocks on a device
value — instrumentation wraps compiled steps from the outside, so
enabling observability never changes what XLA compiles or when the
host syncs (pinned by tests/test_observability.py compile/dispatch
counts).
"""
from bigdl_tpu.observability import tracing as trace  # noqa: F401
from bigdl_tpu.observability.registry import (Counter, Gauge, Histogram,
                                              MetricRegistry,
                                              default_registry,
                                              sanitize_name)
from bigdl_tpu.observability.summary import (Summary, SummaryReader,
                                             TrainSummary,
                                             ValidationSummary)
from bigdl_tpu.observability.tracing import Tracer

__all__ = ["trace", "Tracer", "Counter", "Gauge", "Histogram",
           "MetricRegistry", "default_registry", "sanitize_name",
           "Summary", "TrainSummary", "ValidationSummary",
           "SummaryReader"]

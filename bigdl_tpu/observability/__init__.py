"""bigdl_tpu.observability — traces, metrics, summaries, telemetry.

Host-side observability spanning training and serving (reference
parity: the named per-iteration ``Metrics`` + per-module timing hooks,
SURVEY §2.7/§7, grown into the BigDL line's TrainSummary/
ValidationSummary visualization API — arXiv:1804.05839, 2204.01715).
Pillars:

- ``registry``        — process-wide Counter/Gauge/Histogram registry
  with Prometheus text exposition and a JSON dump
  (:func:`default_registry`).
- ``trace``           — span tracer (``trace.span("device step")``)
  that exports Chrome trace-event JSON for chrome://tracing /
  Perfetto, with explicit host-sync annotations and event taps.
- ``summary``         — TrainSummary/ValidationSummary scalar event
  logs (JSONL) plus :class:`SummaryReader` for replay (live-tail safe).
- ``exporter``        — :class:`MetricsServer`, an opt-in stdlib HTTP
  server exposing /metrics, /metrics.json, /trace, /healthz, /readyz
  over a pluggable :class:`HealthRegistry` (:func:`default_health`).
- ``compile_watch``   — XLA compile/memory telemetry: ``watch()``
  wraps jitted callables, counts compiles by abstract-shape signature,
  exports cost/memory analysis, and warns on recompile storms.
- ``flight_recorder`` — :class:`FlightRecorder`, a bounded black-box
  ring that dumps a postmortem directory on abnormal exit.
- ``request_trace``   — :class:`RequestTracker`, per-request serving
  timelines with tail sampling: end-to-end latency attribution for
  the router/replica plane (:func:`default_tracker`).

HOST-ONLY CONTRACT: nothing in this package imports jax at module top
level (jaxlint rule JX5 enforces it) and nothing here blocks on a
device value — instrumentation wraps compiled steps from the outside,
so enabling observability never changes what XLA compiles or when the
host syncs (pinned by tests/test_observability.py compile/dispatch
counts).
"""
from bigdl_tpu.observability import compile_watch  # noqa: F401
from bigdl_tpu.observability import tracing as trace  # noqa: F401
from bigdl_tpu.observability.exporter import (HealthCheck,
                                              HealthRegistry,
                                              MetricsServer,
                                              default_health)
from bigdl_tpu.observability.flight_recorder import FlightRecorder
from bigdl_tpu.observability.request_trace import (RequestTimeline,
                                                   RequestTracker,
                                                   default_tracker)
from bigdl_tpu.observability.registry import (Counter, Gauge, Histogram,
                                              MetricRegistry,
                                              default_registry,
                                              sanitize_name)
from bigdl_tpu.observability.summary import (Summary, SummaryReader,
                                             TrainSummary,
                                             ValidationSummary)
from bigdl_tpu.observability.tracing import Tracer

__all__ = ["trace", "Tracer", "Counter", "Gauge", "Histogram",
           "MetricRegistry", "default_registry", "sanitize_name",
           "Summary", "TrainSummary", "ValidationSummary",
           "SummaryReader", "MetricsServer", "HealthCheck",
           "HealthRegistry", "default_health", "FlightRecorder",
           "RequestTimeline", "RequestTracker", "default_tracker",
           "compile_watch"]

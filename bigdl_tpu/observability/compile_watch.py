"""Compile telemetry: who compiled, how often, and what it costs.

Recompile storms and HBM footprints are the dominant SILENT performance
killers on TPU — a serving loop that retraces per request length, or a
train step whose peak HBM creeps toward the ceiling, looks healthy in
every throughput metric until it falls over. Spark-era BigDL never had
this failure mode (no tracing JIT); the JAX-native telemetry plane
watches it explicitly.

Three entry points:

- :func:`watch` wraps a callable (jitted or not): every call is keyed
  by the ABSTRACT SHAPE SIGNATURE of its arguments (shapes + dtypes of
  array leaves, values of everything else — the same key jax retraces
  on). A new signature counts as a compile; crossing
  ``storm_threshold`` distinct signatures for one name logs a
  structured recompile-storm warning carrying the offending shape diff.
  For jitted callables (anything with ``.lower``) the first call per
  signature also extracts the executable's ``cost_analysis()`` /
  ``memory_analysis()`` (the extraction perf.py:157,326 does inline).
- :func:`note_compile` records a compile the caller already performed
  (DistriOptimizer's AOT ``.lower().compile()`` path hands its
  executable straight in — zero extra tracing).
- :func:`record_executable` exports one executable's cost/memory table
  as registry gauges (bench.py / collective_bench rows).

Registry series (label ``name``): ``compile_watch_calls_total``,
``compile_watch_compiles_total``, ``compile_watch_signatures``,
``compile_watch_storms_total``, and per-executable gauges
``compile_watch_flops`` / ``_bytes_accessed`` / ``_arg_bytes`` /
``_output_bytes`` / ``_temp_bytes`` / ``_peak_hbm_bytes``. Each compile
also emits a trace instant (cat ``compile_watch``) so retraces are
visible on the Perfetto timeline next to the host spans.

HOST-ONLY CONTRACT: no module-level jax import (jaxlint JX5) — jax is
lazily imported only inside the stats path, and only for abstract
avals. Watching a function never changes what XLA compiles and never
blocks on a device value; stats extraction reuses the jit cache
(verified: ``lower().compile()`` after a call is cache-hit, see
models/utils/perf.py:324).
"""
from __future__ import annotations

import logging
import threading

__all__ = ["CompileWatch", "default_watch", "watch", "note_compile",
           "note_cache_hit", "note_cache_miss", "record_executable",
           "executable_stats", "signature_of", "table", "reset",
           "DEFAULT_STORM_THRESHOLD"]

logger = logging.getLogger("bigdl_tpu.observability.compile_watch")

DEFAULT_STORM_THRESHOLD = 8


def signature_of(args, kwargs=None) -> tuple:
    """Flatten a call's arguments to a hashable abstract signature:
    array-likes contribute ``dtype[shape]``, plain containers recurse,
    everything else contributes its type and (when hashable) value —
    the same information a jit cache keys on, computed host-side."""
    out: list[tuple[str, str]] = []

    def walk(path, x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            dims = ",".join(str(int(d)) for d in shape)
            out.append((path, f"{dtype}[{dims}]"))
        elif isinstance(x, dict):
            for k in sorted(x, key=str):
                walk(f"{path}.{k}", x[k])
        elif isinstance(x, (list, tuple)):
            for i, v in enumerate(x):
                walk(f"{path}[{i}]", v)
        elif isinstance(x, (int, float, bool, str, bytes,
                            type(None))):
            out.append((path, repr(x)))
        else:
            # opaque object (a model, a cache): identity-stable by type
            out.append((path, f"<{type(x).__name__}>"))

    for i, a in enumerate(args):
        walk(f"arg{i}", a)
    for k in sorted(kwargs or {}):
        walk(f"kw:{k}", (kwargs or {})[k])
    return tuple(out)


def _sig_diff(old: tuple | None, new: tuple) -> str:
    """Human-readable leaf-level diff between two signatures — the
    'what changed shape' line a storm warning needs."""
    if old is None:
        return "first signature"
    o, n = dict(old), dict(new)
    parts = []
    for path in sorted(set(o) | set(n)):
        a, b = o.get(path), n.get(path)
        if a != b:
            parts.append(f"{path}: {a or '<absent>'} -> "
                         f"{b or '<absent>'}")
    return "; ".join(parts) if parts else "structure changed"


def executable_stats(executable) -> dict:
    """Cost/memory table of one compiled executable (the extraction
    models/utils/perf.py does inline at :157/:326, shared).

    Every field is best-effort: backends differ in what they expose
    (CPU has cost_analysis but may lack memory_analysis), and telemetry
    must never break the caller."""
    out: dict[str, float] = {}
    try:
        cost = executable.cost_analysis()
    except Exception:
        cost = None
    if isinstance(cost, (list, tuple)):     # older jax returns [dict]
        cost = cost[0] if cost else None
    if cost:
        for key, name in (("flops", "flops"),
                          ("bytes accessed", "bytes_accessed")):
            v = cost.get(key)
            if v is not None:
                out[name] = float(v)
    try:
        mem = executable.memory_analysis()
    except Exception:
        mem = None
    if mem is not None:
        for attr, name in (("argument_size_in_bytes", "arg_bytes"),
                           ("output_size_in_bytes", "output_bytes"),
                           ("temp_size_in_bytes", "temp_bytes"),
                           ("alias_size_in_bytes", "alias_bytes"),
                           ("generated_code_size_in_bytes",
                            "code_bytes")):
            v = getattr(mem, attr, None)
            if v is not None:
                out[name] = float(v)
        if {"arg_bytes", "output_bytes", "temp_bytes"} <= out.keys():
            # aliased (donated) buffers overlap args and outputs —
            # don't double-count them in the peak-HBM estimate
            out["peak_hbm_bytes"] = max(
                out["arg_bytes"] + out["output_bytes"]
                + out["temp_bytes"] - out.get("alias_bytes", 0.0), 0.0)
    return out


class CompileWatch:
    """Per-name compile ledger. One process-wide instance lives behind
    :func:`default_watch`; components take ``watch=``/construct their
    own to isolate (tests do)."""

    _GAUGES = ("flops", "bytes_accessed", "arg_bytes", "output_bytes",
               "temp_bytes", "peak_hbm_bytes")

    def __init__(self, registry=None, tracer=None,
                 storm_threshold: int = DEFAULT_STORM_THRESHOLD):
        if int(storm_threshold) < 2:
            raise ValueError(f"storm_threshold must be >= 2, got "
                             f"{storm_threshold}")
        self._registry = registry
        self._tracer = tracer
        self.storm_threshold = int(storm_threshold)
        self._lock = threading.Lock()
        self._names: dict[str, dict] = {}
        # compile taps: fn(name, n_signatures) on every NEW signature,
        # invoked OUTSIDE the ledger lock; errors swallowed (mirrors
        # Tracer taps). The serving batcher rides one to attribute a
        # compile to the request whose prefill triggered it
        # (observability/request_trace.py).
        self._taps: list = []

    # -- plumbing --
    def _reg(self):
        if self._registry is None:
            from bigdl_tpu.observability.registry import default_registry
            return default_registry()
        return self._registry

    def _trace(self):
        if self._tracer is None:
            from bigdl_tpu.observability.tracing import get_tracer
            return get_tracer()
        return self._tracer

    # -- taps --
    def add_tap(self, fn) -> None:
        """Subscribe ``fn(name, n_signatures)`` to every new-signature
        (= compile) event. Tap errors are swallowed: observability
        must never take down the loop."""
        with self._lock:
            if fn not in self._taps:
                self._taps.append(fn)

    def remove_tap(self, fn) -> None:
        with self._lock:
            if fn in self._taps:
                self._taps.remove(fn)

    def _entry(self, name: str) -> dict:
        e = self._names.get(name)
        if e is None:
            e = self._names[name] = {
                "calls": 0, "compiles": 0, "storms": 0,
                "signatures": {},       # sig -> call count
                "last_signature": None, "stats": {},
            }
        return e

    # -- recording --
    def note_call(self, name: str, signature: tuple,
                  storm_threshold: int | None = None) -> bool:
        """Count one call; returns True when ``signature`` is new for
        ``name`` (i.e. this call compiled)."""
        threshold = int(storm_threshold or self.storm_threshold)
        with self._lock:
            e = self._entry(name)
            e["calls"] += 1
            new = signature not in e["signatures"]
            prev = e["last_signature"]
            if new:
                e["signatures"][signature] = 0
                e["compiles"] += 1
                e["last_signature"] = signature
            e["signatures"][signature] += 1
            n_sigs = len(e["signatures"])
            storm = new and n_sigs >= threshold
            if storm:
                e["storms"] += 1
        reg = self._reg()
        reg.counter("compile_watch_calls_total",
                    "calls through compile_watch-wrapped functions",
                    labelnames=("name",)).inc(name=name)
        if new:
            reg.counter("compile_watch_compiles_total",
                        "distinct abstract-shape signatures "
                        "(= compiles) per watched name",
                        labelnames=("name",)).inc(name=name)
            reg.gauge("compile_watch_signatures",
                      "live distinct signatures per watched name",
                      labelnames=("name",)).set(n_sigs, name=name)
            self._trace().instant("compile", cat="compile_watch",
                                  watch=name, signatures=n_sigs)
            for tap in list(self._taps):
                try:
                    tap(name, n_sigs)
                except Exception:
                    pass
        if storm:
            diff = _sig_diff(prev, signature)
            reg.counter("compile_watch_storms_total",
                        "recompile-storm warnings fired",
                        labelnames=("name",)).inc(name=name)
            self._trace().instant("recompile storm",
                                  cat="compile_watch", watch=name,
                                  signatures=n_sigs, diff=diff)
            logger.warning(
                "recompile storm: %r has %d distinct compile "
                "signatures (threshold %d) — every new shape pays a "
                "full XLA compile; pad/bucket the offending input. "
                "Newest shape diff: %s", name, n_sigs, threshold, diff)
        return new

    def note_cache_hit(self, name: str) -> None:
        """One AOT-cache hit for ``name`` (tuning/aot_cache.py): the
        executable was deserialized instead of compiled."""
        with self._lock:
            e = self._entry(name)
            e["cache_hits"] = e.get("cache_hits", 0) + 1
        self._reg().counter(
            "tuning_cache_hits_total",
            "AOT executable cache hits (deserialized, not compiled)",
            labelnames=("name",)).inc(name=name)
        self._trace().instant("aot cache hit", cat="compile_watch",
                              watch=name)

    def note_cache_miss(self, name: str, reason: str) -> None:
        """One AOT-cache miss for ``name`` with its reason (absent /
        deserialize_failed / ...) — the caller falls back to a fresh
        compile."""
        with self._lock:
            e = self._entry(name)
            e["cache_misses"] = e.get("cache_misses", 0) + 1
        self._reg().counter(
            "tuning_cache_misses_total",
            "AOT executable cache misses (fresh compile follows)",
            labelnames=("name",)).inc(name=name)
        self._trace().instant("aot cache miss", cat="compile_watch",
                              watch=name, reason=reason)
        logger.info("tuning_cache_miss name=%s reason=%s", name, reason)

    def note_compile(self, name: str, signature, executable=None):
        """Record a compile the caller performed itself (AOT
        ``.lower().compile()`` paths). ``signature`` may be any
        key with a stable repr; ``executable`` adds its cost/memory
        table."""
        self.note_call(name, (("key", repr(signature)),))
        if executable is not None:
            self.record_executable(name, executable)

    def record_executable(self, name: str, executable) -> dict:
        """Export one executable's cost/memory table as gauges and
        remember it in the per-name ledger. Returns the table."""
        stats = executable_stats(executable)
        with self._lock:
            self._entry(name)["stats"] = dict(stats)
        reg = self._reg()
        for key in self._GAUGES:
            if key in stats:
                reg.gauge(f"compile_watch_{key}",
                          f"latest executable {key.replace('_', ' ')} "
                          "per watched name",
                          labelnames=("name",)).set(stats[key],
                                                    name=name)
        return stats

    # -- the wrapper --
    def watch(self, fn, *, name: str | None = None,
              storm_threshold: int | None = None, stats: bool = True):
        """Wrap ``fn`` with signature-keyed compile counting.

        ``stats=True`` (default) extracts cost/memory analysis on each
        new signature when ``fn`` has the jit AOT surface (``.lower``)
        — abstract avals only, compile-cache shared with the live call.
        ``stats=False`` is pure counting for hot loops that must add
        zero tracing work (LocalOptimizer's step).
        """
        import functools
        label = name or getattr(fn, "__name__", None) or repr(fn)
        can_stats = stats and hasattr(fn, "lower")

        @functools.wraps(fn, updated=())
        def wrapped(*args, **kwargs):
            sig = signature_of(args, kwargs)
            new = self.note_call(label, sig, storm_threshold)
            abstract = None
            if new and can_stats:
                abstract = _abstractify(args, kwargs)
            out = fn(*args, **kwargs)
            if abstract is not None:
                try:
                    self.record_executable(
                        label, fn.lower(*abstract[0],
                                        **abstract[1]).compile())
                except Exception as e:    # telemetry never breaks math
                    logger.debug("compile stats for %r unavailable: %s",
                                 label, e)
            return out

        wrapped.__wrapped__ = fn
        wrapped.watch_name = label
        return wrapped

    # -- inspection --
    def table(self) -> dict:
        """JSON-able per-name ledger (the flight recorder dumps this):
        calls / compiles / storms / signature list with call counts /
        latest executable stats."""
        with self._lock:
            out = {}
            for name, e in sorted(self._names.items()):
                out[name] = {
                    "calls": e["calls"], "compiles": e["compiles"],
                    "storms": e["storms"],
                    "cache_hits": e.get("cache_hits", 0),
                    "cache_misses": e.get("cache_misses", 0),
                    "signatures": [
                        {"signature": ["=".join(p) for p in sig],
                         "calls": count}
                        for sig, count in e["signatures"].items()],
                    "stats": dict(e["stats"]),
                }
        return out

    def reset(self) -> None:
        with self._lock:
            self._names.clear()


def _abstractify(args, kwargs):
    """Replace array leaves with ShapeDtypeStructs so ``.lower`` can
    run without live buffers (donated args are consumed by the real
    call). jax import is function-local (JX5)."""
    import jax

    def leaf(x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None or dtype is None:
            return x
        try:
            return jax.ShapeDtypeStruct(
                shape, dtype, weak_type=bool(getattr(x, "weak_type",
                                                     False)))
        except TypeError:           # older ShapeDtypeStruct signature
            return jax.ShapeDtypeStruct(shape, dtype)

    def walk(x):
        if isinstance(x, dict):
            return {k: walk(v) for k, v in x.items()}
        if isinstance(x, tuple):
            return tuple(walk(v) for v in x)
        if isinstance(x, list):
            return [walk(v) for v in x]
        return leaf(x)

    return walk(tuple(args)), walk(dict(kwargs))


_DEFAULT = CompileWatch()


def default_watch() -> CompileWatch:
    """The process-wide compile ledger (pass ``watch=`` / construct a
    CompileWatch to isolate)."""
    return _DEFAULT


def watch(fn, *, name=None, storm_threshold=None, stats=True):
    return _DEFAULT.watch(fn, name=name, storm_threshold=storm_threshold,
                          stats=stats)


def note_compile(name, signature, executable=None):
    return _DEFAULT.note_compile(name, signature, executable)


def note_cache_hit(name):
    return _DEFAULT.note_cache_hit(name)


def note_cache_miss(name, reason):
    return _DEFAULT.note_cache_miss(name, reason)


def record_executable(name, executable):
    return _DEFAULT.record_executable(name, executable)


def table() -> dict:
    return _DEFAULT.table()


def reset() -> None:
    _DEFAULT.reset()

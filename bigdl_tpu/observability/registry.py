"""Process-wide metric registry: Counter / Gauge / Histogram.

The operator-facing measurement substrate (reference parity: the named
per-iteration ``Metrics`` accumulators, optim/Metrics.scala:24-117, grown
the way the BigDL line grew them into first-class visualization tooling —
arXiv:1804.05839 §5, arXiv:2204.01715). Three instrument kinds:

- :class:`Counter`   — monotonically increasing totals (admissions,
  retirements, tokens generated).
- :class:`Gauge`     — last-write-wins level readings (queue depth, KV
  page-pool utilization, collective bytes per step).
- :class:`Histogram` — FIXED bucket boundaries chosen at registration
  (latency distributions: step time, TTFT, per-token decode latency).
  Fixed boundaries keep merges/exposition O(buckets) and allocation-free
  per observation.

Instruments carry optional label dimensions; ``(name, label values)``
identifies a time series. Exposition: :meth:`MetricRegistry.expose`
emits Prometheus text format; :meth:`MetricRegistry.dump` a JSON-able
dict (same data, for harnesses that want structured output).

HOST-ONLY CONTRACT: this module never imports jax (enforced by
dev/lint.py) and every operation is a lock + dict update on host memory
— safe to call at any frequency from training/serving loops, and
incapable of adding a device sync to a compiled step.
"""
from __future__ import annotations

import json
import math
import os
import re
import threading
import time

__all__ = ["Counter", "Gauge", "Histogram", "MetricRegistry",
           "default_registry", "sanitize_name", "DEFAULT_BUCKETS"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# seconds-oriented latency boundaries: 0.5ms .. 10s (+Inf implicit)
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def sanitize_name(name: str) -> str:
    """Map an arbitrary display name ("device step time") onto the
    exposition charset (``device_step_time``)."""
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", str(name).strip())
    if not out or not _NAME_RE.match(out):
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "", labelnames=()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r} "
                             "(use sanitize_name)")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._series: dict[tuple, object] = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}")
        return tuple(str(labels[ln]) for ln in self.labelnames)

    def _labelstr(self, key: tuple, extra: str = "") -> str:
        parts = [f'{ln}="{_escape(v)}"'
                 for ln, v in zip(self.labelnames, key)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""


class Counter(_Metric):
    """Monotonic total. ``inc`` only; negative increments are a bug."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError(
                f"counter {self.name} cannot decrease (inc {amount})")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(amount)

    def value(self, **labels) -> float:
        return float(self._series.get(self._key(labels), 0.0))


class Gauge(_Metric):
    """Level reading; last write wins."""

    kind = "gauge"

    def set(self, value: float, **labels):
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(amount)

    def dec(self, amount: float = 1.0, **labels):
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        return float(self._series.get(self._key(labels), 0.0))


class Histogram(_Metric):
    """Distribution with FIXED bucket boundaries (upper bounds,
    cumulative in exposition; +Inf implicit).

    ``observe(v, exemplar="rid-42")`` additionally remembers the
    observation as the bucket's last EXEMPLAR — a trace id linking the
    aggregate series back to one concrete request timeline
    (``/requests/<id>``). Exemplars ride exposition OpenMetrics-style
    (``... # {trace_id="rid-42"} 0.37 <unix ts>``) and ``dump()``;
    ``snapshot()`` stays exemplar-free so merges are unchanged."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        bs = tuple(float(b) for b in buckets)
        if not bs or list(bs) != sorted(set(bs)) or bs[-1] == math.inf:
            raise ValueError(
                f"histogram {name} buckets must be strictly increasing "
                f"finite upper bounds, got {buckets}")
        self.buckets = bs

    def observe(self, value: float, exemplar: str | None = None,
                **labels):
        key = self._key(labels)
        v = float(value)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                st = {"counts": [0] * (len(self.buckets) + 1),
                      "sum": 0.0, "count": 0}
                self._series[key] = st
            i = 0
            while i < len(self.buckets) and v > self.buckets[i]:
                i += 1
            st["counts"][i] += 1
            st["sum"] += v
            st["count"] += 1
            if exemplar is not None:
                # last exemplar per bucket index, created lazily so
                # exemplar-free histograms carry zero extra state
                ex = st.get("exemplars")
                if ex is None:
                    ex = st["exemplars"] = {}
                ex[i] = {"trace_id": str(exemplar), "value": v,
                         "ts": time.time()}

    def snapshot(self, **labels) -> dict:
        """Cumulative per-bucket counts plus sum/count:
        ``{"buckets": {le_str: n}, "sum": s, "count": n}``."""
        key = self._key(labels)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                return {"buckets": {}, "sum": 0.0, "count": 0}
            counts = list(st["counts"])
            total = float(st["sum"])
            n = int(st["count"])
        cum, out = 0, {}
        for b, c in zip(self.buckets + (math.inf,), counts):
            cum += c
            out[_fmt(b)] = cum
        return {"buckets": out, "sum": total, "count": n}


class MetricRegistry:
    """Name -> instrument map with idempotent get-or-create and text /
    JSON exposition. One process-wide default lives behind
    :func:`default_registry`; tests construct their own."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}, requested {cls.kind}")
                if m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} labelnames {m.labelnames} != "
                        f"requested {tuple(labelnames)}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()

    def _collect(self):
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def expose(self) -> str:
        """Prometheus text exposition format."""
        lines = []
        for m in self._collect():
            if m.help:
                lines.append(f"# HELP {m.name} {_escape(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            with m._lock:
                series = dict(m._series)
            for key in sorted(series):
                if isinstance(m, Histogram):
                    st = series[key]
                    exemplars = st.get("exemplars") or {}
                    cum = 0
                    for i, (b, c) in enumerate(
                            zip(m.buckets + (math.inf,),
                                st["counts"])):
                        cum += c
                        lbl = m._labelstr(key,
                                          f'le="{_fmt(b)}"')
                        ex = exemplars.get(i)
                        tail = ""
                        if ex is not None:
                            # OpenMetrics exemplar syntax
                            tail = (f' # {{trace_id="'
                                    f'{_escape(ex["trace_id"])}"}} '
                                    f'{_fmt(ex["value"])} '
                                    f'{ex["ts"]:.3f}')
                        lines.append(f"{m.name}_bucket{lbl} {cum}"
                                     f"{tail}")
                    lines.append(f"{m.name}_sum{m._labelstr(key)} "
                                 f"{_fmt(st['sum'])}")
                    lines.append(f"{m.name}_count{m._labelstr(key)} "
                                 f"{st['count']}")
                else:
                    lines.append(f"{m.name}{m._labelstr(key)} "
                                 f"{_fmt(series[key])}")
        return "\n".join(lines) + ("\n" if lines else "")

    def dump(self) -> dict:
        """JSON-able mirror of :meth:`expose`."""
        out = {}
        for m in self._collect():
            samples = []
            with m._lock:
                series = dict(m._series)
            for key in sorted(series):
                labels = dict(zip(m.labelnames, key))
                if isinstance(m, Histogram):
                    st = series[key]
                    cum, buckets = 0, {}
                    for b, c in zip(m.buckets + (math.inf,),
                                    st["counts"]):
                        cum += c
                        buckets[_fmt(b)] = cum
                    sample = {"labels": labels,
                              "buckets": buckets,
                              "sum": float(st["sum"]),
                              "count": int(st["count"])}
                    exemplars = st.get("exemplars")
                    if exemplars:
                        bounds = m.buckets + (math.inf,)
                        sample["exemplars"] = {
                            _fmt(bounds[i]): dict(ex)
                            for i, ex in sorted(exemplars.items())}
                    samples.append(sample)
                else:
                    samples.append({"labels": labels,
                                    "value": float(series[key])})
            out[m.name] = {"type": m.kind, "help": m.help,
                           "labelnames": list(m.labelnames),
                           "samples": samples}
        return out

    def dump_json(self, path: str | None = None) -> str:
        text = json.dumps(self.dump(), indent=2, sort_keys=True)
        if path is not None:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                f.write(text)
        return text


_DEFAULT = MetricRegistry()


def default_registry() -> MetricRegistry:
    """The process-wide registry every subsystem records into by
    default (pass ``registry=`` to instrumented components to
    isolate)."""
    return _DEFAULT

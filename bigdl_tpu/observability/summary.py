"""TrainSummary / ValidationSummary — portable scalar event logs.

Reference-parity naming: the BigDL line's visualization API
(``TrainSummary`` / ``ValidationSummary``, arXiv:1804.05839 §5;
"BigDL 2.0" arXiv:2204.01715) records per-step scalars the operator
replays in a dashboard. Instead of TF event protos the log here is
PORTABLE JSONL: one ``{"step", "wall_time", "tag", "value"}`` object
per line, append-only, flushed per write — readable with one
``json.loads`` per line from any language, and safe to tail while the
run is live.

Writers take HOST floats (the training loop has already paid the
``float(loss)`` sync it needed anyway); a summary never forces a
device readback of its own. :class:`SummaryReader` replays a log into
per-tag ``(step, wall_time, value)`` series for plotting/regression
checks.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["Summary", "TrainSummary", "ValidationSummary",
           "SummaryReader"]


class Summary:
    """Append-only scalar event log at ``{log_dir}/{app_name}/
    {kind}.jsonl``. Subclasses fix ``kind``; the base class is usable
    directly for ad-hoc logs (e.g. a serving session)."""

    kind = "events"

    def __init__(self, log_dir: str, app_name: str = "bigdl"):
        self.log_dir = log_dir
        self.app_name = app_name
        d = os.path.join(log_dir, app_name)
        os.makedirs(d, exist_ok=True)
        self.path = os.path.join(d, f"{self.kind}.jsonl")
        self._lock = threading.Lock()
        self._f = open(self.path, "a", encoding="utf-8")

    def add_scalar(self, tag: str, value: float, step: int):
        """Append one ``(step, wall_time, tag, value)`` event.
        ``value`` must already be a host number — pass ``float(loss)``,
        never a live device array."""
        rec = {"step": int(step), "wall_time": time.time(),
               "tag": str(tag), "value": float(value)}
        line = json.dumps(rec)
        with self._lock:
            if self._f.closed:
                raise ValueError(f"summary {self.path} is closed")
            self._f.write(line + "\n")
            self._f.flush()
        return self

    def read_scalar(self, tag: str) -> list[tuple[int, float, float]]:
        """Replay this log's series for ``tag`` (see
        :meth:`SummaryReader.scalars`)."""
        return SummaryReader(self.path).scalars(tag)

    def tags(self) -> list[str]:
        return SummaryReader(self.path).tags()

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class TrainSummary(Summary):
    """Per-iteration training scalars (Loss / Throughput /
    HostInputTime / DeviceStepTime, plus whatever callers add)."""

    kind = "train"


class ValidationSummary(Summary):
    """Validation scalars, one event per method per validation pass."""

    kind = "validation"


class SummaryReader:
    """Replay a summary JSONL log (pass the ``.jsonl`` path or a
    summary object's ``.path``)."""

    def __init__(self, path: str):
        self.path = path

    def records(self) -> list[dict]:
        """Parse every complete record. Tailing a LIVE file can catch
        the writer mid-line: a final line with no terminating newline
        is an in-flight write and is skipped (only that one). A
        newline-TERMINATED corrupt line is real corruption and still
        fails loudly."""
        with open(self.path, encoding="utf-8") as f:
            text = f.read()
        terminated = text.endswith("\n")
        lines = text.split("\n")
        if lines and lines[-1] == "":
            lines.pop()
        out = []
        for ln, line in enumerate(lines, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                if ln == len(lines) and not terminated:
                    break          # live tail: incomplete final line
                raise ValueError(
                    f"{self.path}:{ln}: corrupt summary line "
                    f"({e})") from e
            out.append(rec)
        return out

    def tags(self) -> list[str]:
        return sorted({r["tag"] for r in self.records()})

    def scalars(self, tag: str) -> list[tuple[int, float, float]]:
        """``[(step, wall_time, value), ...]`` in file (= write)
        order."""
        return [(int(r["step"]), float(r["wall_time"]),
                 float(r["value"]))
                for r in self.records() if r["tag"] == tag]

    def steps(self, tag: str) -> list[int]:
        return [s for s, _, _ in self.scalars(tag)]

    def values(self, tag: str) -> list[float]:
        return [v for _, _, v in self.scalars(tag)]
